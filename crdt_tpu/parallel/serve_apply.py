"""``mesh_serve_apply`` — the tenant-packed serving dispatch (ISSUE 15).

One jitted shard_map applies a whole coalesced :class:`OpSlab` to a
tenant superblock: the tenant axis shards over the REPLICA mesh axis
(tenants are independent — zero cross-tenant collectives), each device
gathers its touched rows, runs the S-step vmapped op scan
(ops/superblock.py), and scatters the rows back IN PLACE on the donated
buffer (the PR 3 zero-copy discipline; ``tools/check_aliasing.py``
covers this entry through the registry like every other donating one).

Index convention: ``idx[B] int32`` carries LOCAL row indices — lane
block ``[r·B/P, (r+1)·B/P)`` belongs to mesh rank ``r`` and its values
index that rank's local tenant rows ``[0, T/P)``; ``-1`` lanes are
empty (their slots are NOOP and their scatter drops). The host-side
ingest queue (crdt_tpu/serve/ingest.py) owns this layout and the
at-most-one-lane-per-tenant contract that makes the scatter
conflict-free.

``telemetry=`` follows the house rules: off traces the byte-identical
flag-free program; on returns a :class:`~crdt_tpu.telemetry.Telemetry`
sidecar (slots changed by the applied ops psum'd over the replica axis,
slab wire bytes over all devices, deferred-depth / widen-pressure
gauges over the TOUCHED rows — the serving-tier gauges
``live_tenants`` / ``evicted_tenants`` / ``ingest_coalesced_ops`` /
``hist_ingest_batch`` are filled host-side by the serve layer, the
``stream_*``/``wal_*`` discipline).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry as tele
from ..ops import superblock as sb_ops
from .anti_entropy import _cached
from .mesh import ELEMENT_AXIS, REPLICA_AXIS


def _validate(state, slab: sb_ops.OpSlab, idx, p: int) -> None:
    t = jax.tree.leaves(state)[0].shape[0]
    b = slab.kind.shape[0]
    if t % p:
        raise ValueError(
            f"{t} tenant rows do not divide the {p}-way replica axis"
        )
    if b % p or idx.shape[0] != b:
        raise ValueError(
            f"slab lanes ({b}) and idx ({idx.shape[0]}) must match and "
            f"divide the {p}-way replica axis"
        )


def mesh_serve_apply(
    state,
    slab: sb_ops.OpSlab,
    idx,
    mesh: Mesh,
    *,
    kind: str = "orswot",
    donate: bool = False,
    telemetry: bool = False,
    sync: bool = True,
):
    """Apply one coalesced op slab to a tenant superblock, sharded over
    the replica mesh axis. Returns ``(state, overflow[B])`` — or
    ``(state, overflow, Telemetry)`` with ``telemetry=True``.
    ``overflow`` flags tenants whose bounded buffers could not take an
    op (deferred parking / sparse dot capacity): the serve layer's
    widen-before-retry signal (crdt_tpu/serve/superblock.py).

    ``sync=False`` skips the telemetry path's block-until-ready + host
    dispatch timing and returns the in-flight arrays immediately — the
    pipelined serving loop's issue half (crdt_tpu/serve/loop.py owns
    the completion wait and folds ``hist_dispatch_us`` itself; the
    compiled program is the SAME either way — ``sync`` is host-side
    post-processing only, never part of the jit cache key)."""
    tk = sb_ops.tenant_kind(kind)
    p = mesh.shape[REPLICA_AXIS]
    _validate(state, slab, idx, p)
    idx = jnp.asarray(idx, jnp.int32)
    slot_bytes = tele.shipped_bytes(slab) // max(
        slab.kind.shape[0] * slab.kind.shape[1], 1
    )

    def build():
        def body(state, slab, idx):
            tl = jax.tree.leaves(state)[0].shape[0]
            safe = jnp.clip(idx, 0, tl - 1)
            rows = jax.tree.map(lambda x: x[safe], state)
            new_rows, of = sb_ops.apply_slab_rows(tk, rows, slab)
            valid = idx >= 0
            scatter = jnp.where(valid, idx, tl)
            out = jax.tree.map(
                lambda x, r: x.at[scatter].set(r, mode="drop"),
                state, new_rows,
            )
            of = of & valid
            if not telemetry:
                return out, of
            both = (REPLICA_AXIS, ELEMENT_AXIS)
            n_ops = jnp.sum(slab.kind != sb_ops.NOOP, dtype=jnp.float32)
            tel = tele.zeros()._replace(
                slots_changed=lax.psum(
                    tk.changed(rows, new_rows), REPLICA_AXIS
                ),
                # The slab is the serving tier's wire: every device
                # (element-axis copies included) physically receives
                # its staged shard per dispatch.
                bytes_exchanged=lax.psum(
                    jnp.float32(tele.shipped_bytes(slab)), both
                ),
                bytes_useful=lax.psum(n_ops * slot_bytes, both),
                deferred_depth=lax.pmax(tele.device_depth(new_rows), both),
                widen_pressure=lax.pmax(
                    tele.device_pressure(new_rows), both
                ),
            )
            return out, of, tel

        row_spec = P(REPLICA_AXIS)
        out_state = jax.tree.map(lambda _: row_spec, state)
        out_specs = (out_state, row_spec) + (
            (tele.specs(),) if telemetry else ()
        )
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(out_state, jax.tree.map(lambda _: row_spec, slab),
                      row_spec),
            out_specs=out_specs,
            check_vma=False,
        )

    fn = _cached(
        "serve_apply", (state, slab, idx), mesh, build, kind, telemetry,
        donate_argnums=(0,) if donate else (),
    )
    t0 = time.perf_counter()
    out = fn(state, slab, idx)
    if telemetry:
        if not sync:
            return out
        jax.block_until_ready(out)
        state, of, tel = out
        tel = tele.time_dispatch(tel, time.perf_counter() - t0)
        return state, of, tel
    return out


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _example(mesh: Mesh, kind: str = "orswot"):
    p = mesh.shape[REPLICA_AXIS]
    caps = dict(n_elems=4, n_actors=2, deferred_cap=2)
    tk = sb_ops.tenant_kind(kind)
    t, b, s = p * 4, p * 2, 2
    state = tk.empty(**caps, batch=(t,))
    slab = sb_ops.empty_slab(tk, caps, b, s)
    import numpy as np

    idx = jnp.asarray(np.tile(np.arange(b // p, dtype=np.int32), p))
    return state, slab, idx


def _register() -> None:
    from ..analysis.registry import register_entry_point

    register_entry_point(
        "mesh_serve_apply",
        kind="serve_apply",
        make_args=_example,
        invoke=lambda mesh, args: mesh_serve_apply(
            args[0], args[1], args[2], mesh, donate=True
        ),
        n_donated=1,
    )


_register()

__all__ = ["mesh_serve_apply"]
