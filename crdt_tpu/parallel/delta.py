"""δ-state anti-entropy: ship bounded deltas, not whole states.

The delta-CRDT line (Almeida et al., "Efficient State-based CRDTs by
Delta-Mutation" / "Delta State Replicated Data Types" — PAPERS.md) keeps
state-based convergence but exchanges join-decompositions: only the
sub-state that changed since the last exchange. The reference crate has
no delta support; BASELINE config 3 names a "delta-state anti-entropy
round" as the shape of the headline workload, and this module is that
mode for the dense TPU slabs.

TPU form (static shapes, no dynamic sparsity): each replica carries a
``dirty[E]`` row mask and an ``fctx[E, A]`` per-row FORWARDING CONTEXT —
for each changed element, the clock of every dot whose fate the replica
can attest for that element (its live dots plus the dots it saw removed
there). A delta round ships a fixed-size ``DeltaPacket`` of up to
``cap`` (index, row, row-context) triples plus the bounded parked-remove
buffer.

Why per-row contexts and NOT the sender's top clock: a packet is a
join-decomposition only if every dot its context covers is accounted for
by its store. Shipping the full top with a partial row set lets the
receiver's context outrun its rows; when the receiver later forwards a
row under that inflated context, downstream peers read the missing dots
as removals and wrongly kill live entries (a real failure mode — pinned
by tests/test_delta.py). Worse, clock coverage is a per-actor PREFIX:
even a row-scoped context covering (a, c) implicitly covers (a, c') for
c' < c — dots of OTHER rows — so contexts may never be folded into the
receiver's top at all (pinned by the capped depth-3 drain test). The
top therefore stays FROZEN at the local-fold value through the ring
(rows always reflect it); packet-learned knowledge lives in the
per-row fctx, and the ring's final top-closure collective restores the
exact full-join top from the untouched local tops.

The receiver scatter-joins packet rows under (receiver top, packet row
context) — the full ``ops.orswot.join`` survival rule restricted to the
packet rows — and re-marks every row the packet carried (domain
forwarding: the row's interpreting context grew even if its dots did
not), which propagates deltas transitively around the ring. A sender
clears rows it ships; residue past ``cap`` stays dirty and drains over
subsequent rounds (bounded backlog, no loss).

Tracking contract: accumulate (dirty, fctx) with ``interval_accumulate``
at op granularity — or any granularity fine enough that no dot is both
born and removed between two accumulation points — starting from a
moment the replicas were mutually synced (genesis counts). Bandwidth per
round per link is O(cap·2A + D·E/8) instead of O(E·A).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops.orswot import (
    OrswotState,
    _apply_parked,
    _compact_deferred,
    _dedupe_deferred,
)
from .mesh import (
    ELEMENT_AXIS,
    REPLICA_AXIS,
    orswot_specs,
    pad_elements,
    pad_replicas,
)


class DeltaPacket(NamedTuple):
    """One replica's bounded delta (shard-local element indices)."""

    idx: jax.Array    # [C] int32
    rows: jax.Array   # [C, A]  live dots of the shipped elements
    ctxs: jax.Array   # [C, A]  per-row causal context (dots accounted for)
    valid: jax.Array  # [C] bool
    dcl: jax.Array    # [D, A]  parked removes ride whole (bounded)
    dmask: jax.Array  # [D, E]
    dvalid: jax.Array # [D]


def interval_accumulate(
    dirty: jax.Array, fctx: jax.Array, old: OrswotState, new: OrswotState
) -> Tuple[jax.Array, jax.Array]:
    """Fold one mutation step into the (dirty, fctx) tracking pair:
    changed rows become dirty and their context absorbs both endpoint
    rows (a dot the old row held and the new row lacks is a dot this
    replica saw removed — that knowledge must ride the delta)."""
    changed = jnp.any(old.ctr != new.ctr, axis=-1)
    grown = jnp.maximum(fctx, jnp.maximum(old.ctr, new.ctr))
    return dirty | changed, jnp.where(changed[..., None], grown, fctx)


def dirty_between(old: OrswotState, new: OrswotState) -> jax.Array:
    """Row mask of elements whose dot rows differ."""
    return jnp.any(old.ctr != new.ctr, axis=-1)


def extract_delta(
    state: OrswotState,
    dirty: jax.Array,
    fctx: jax.Array,
    cap: int,
    start=0,
) -> Tuple[DeltaPacket, jax.Array, jax.Array]:
    """Pack up to ``cap`` dirty rows with their contexts and clear them
    locally (the ring delivers reliably; residue past ``cap`` drains
    next round). ``start`` rotates the scan origin — domain-forwarded
    rows re-enter the queue, so a fixed lowest-index-first order would
    starve high-index rows under a small cap; rotating by
    ``round * cap`` round-robins every row a slot within E/cap rounds.
    Returns ``(packet, dirty, fctx)``."""
    e = dirty.shape[-1]
    pos = (jnp.arange(e) - start) % e
    order = jnp.argsort(jnp.where(dirty, pos, e + pos))
    idx = order[:cap].astype(jnp.int32)
    valid = jnp.take(dirty, idx)
    rows = jnp.take(state.ctr, idx, axis=0)
    ctxs = jnp.maximum(jnp.take(fctx, idx, axis=0), rows)
    pkt = DeltaPacket(
        idx=idx,
        rows=jnp.where(valid[:, None], rows, 0),
        ctxs=jnp.where(valid[:, None], ctxs, 0),
        valid=valid,
        dcl=state.dcl,
        dmask=state.dmask,
        dvalid=state.dvalid,
    )
    # fctx is NEVER cleared: it is a monotone knowledge cache, not a
    # send queue. Clearing it on ship would let a later stale packet
    # (carrying a dot live under a non-covering context) resurrect a
    # removal this replica had already learned — with the top frozen
    # mid-ring, fctx is the only receiver-side record of packet-learned
    # removals, and monotone knowledge makes convergence monotone.
    return pkt, dirty.at[idx].set(False), fctx


def apply_delta(
    state: OrswotState, pkt: DeltaPacket, dirty: jax.Array, fctx: jax.Array
) -> Tuple[OrswotState, jax.Array, jax.Array, jax.Array]:
    """Join a delta into ``state``: per-row orswot survival under
    (receiver top, packet row context) — ops.orswot.join restricted to
    the packet rows — plus the full deferred union/replay/compaction.
    The receiver's top and per-row forwarding contexts absorb only the
    packet's row-scoped knowledge. Returns
    ``(state, dirty, fctx, overflow)``."""
    recv = jnp.take(state.ctr, pkt.idx, axis=0)  # [C, A]
    # Receiver-side knowledge stays PER-CELL: its honest top (the local
    # fold's — rows reflect it) joined with what packets taught it about
    # THIS cell (fctx). The top itself must NOT grow mid-ring: clock
    # coverage is a per-actor prefix, so a cell-scoped context covering
    # (a, c) implicitly covers (a, c') for c' < c — dots of OTHER cells.
    # Folding such a context into the global top makes the receiver
    # claim observed-and-removed for rows it never saw, and genuine rows
    # arriving later get dropped (found the hard way at depth 3 — the
    # capped map3 drain test pins it). The ring's final top closure
    # restores the exact full-join top from the untouched local tops.
    rctx = jnp.maximum(state.top[None, :], jnp.take(fctx, pkt.idx, axis=0))
    wa = jnp.where(recv > pkt.ctxs, recv, 0)
    wb = jnp.where(pkt.rows > rctx, pkt.rows, 0)
    pa = jnp.any(recv > 0, axis=-1)
    pb = jnp.any(pkt.rows > 0, axis=-1)
    common = jnp.maximum(jnp.minimum(recv, pkt.rows), jnp.maximum(wa, wb))
    new = jnp.where(
        (pa & pb)[:, None],
        common,
        jnp.where((pa & ~pb)[:, None], wa, jnp.where((pb & ~pa)[:, None], wb, 0)),
    ).astype(recv.dtype)
    new = jnp.where(pkt.valid[:, None], new, recv)
    ctr = state.ctr.at[pkt.idx].set(new)
    top = state.top

    # Deferred union — identical tail to ops.orswot.join (rm clocks are
    # their own contexts, so parked removes ship whole and stay sound).
    dcl = jnp.concatenate([state.dcl, pkt.dcl], axis=-2)
    dmask = jnp.concatenate([state.dmask, pkt.dmask], axis=-2)
    dvalid = jnp.concatenate([state.dvalid, pkt.dvalid], axis=-1)
    dcl, dmask, dvalid = _dedupe_deferred(dcl, dmask, dvalid)
    before = ctr
    ctr = _apply_parked(ctr, dcl, dmask, dvalid)
    still_ahead = ~jnp.all(dcl <= top[None, :], axis=-1)
    dvalid = dvalid & still_ahead
    cap_d = state.dcl.shape[-2]
    dcl, dmask, dvalid, overflow = _compact_deferred(dcl, dmask, dvalid, cap_d)

    # Forward on packet DOMAIN, not on content change: a remove-delta
    # can land on a row the receiver already lacks — nothing changes
    # locally, but downstream peers may still hold the dots, so the
    # (row, context) pair keeps riding the ring. Finite `rounds` bounds
    # the redundant re-circulation.
    old_f = jnp.take(fctx, pkt.idx, axis=0)
    new_f = jnp.where(
        pkt.valid[:, None], jnp.maximum(jnp.maximum(old_f, pkt.ctxs), new), old_f
    )
    fctx = fctx.at[pkt.idx].set(new_f)
    dirty = dirty.at[pkt.idx].set(jnp.take(dirty, pkt.idx) | pkt.valid)
    dirty = dirty | jnp.any(ctr != before, axis=-1)
    fctx = jnp.maximum(fctx, jnp.where(jnp.any(ctr != before, axis=-1)[:, None], before, 0))
    out = OrswotState(top=top, ctr=ctr, dcl=dcl, dmask=dmask, dvalid=dvalid)
    return out, dirty, fctx, jnp.any(overflow)


def gate_delta(pkt: DeltaPacket, digest: jax.Array) -> DeltaPacket:
    """Digest gate: invalidate packet slots that provably cannot change
    the receiver, judged against the receiver's digest clock (its
    frozen local-fold top, shipped once before the ring by
    ``run_delta_ring``). This is the FIRST of two redundancy layers —
    stateless top inference, no round-trip memory, fires from round 0;
    the second is the per-link ack window (``ack_window=True``,
    crdt_tpu/delta_opt/ackwin.py), which masks what the peer has
    POSITIVELY confirmed joining — including the removal-carrying slots
    this gate must always ship. A slot is redundant here only when BOTH
    hold:

    - ``ctxs == rows`` lane-wise — the slot attests NO removals: every
      dot its context accounts for is live in its row. A context lane
      above the row is removal knowledge (the sender saw that dot die),
      and a top digest can never prove the receiver knows a removal —
      a dot covered by both tops may be live at one store and removed
      at the other; that asymmetry is exactly what observed-remove
      resolves, so removal-carrying slots always ship.
    - ``rows <= digest`` — the receiver's honest top covers every live
      dot, so its store already accounts for each one (same dot live,
      or removed under its own covering context); joining the add-only
      slot is a content no-op either way.

    Dropping the slot's domain-forwarding re-mark is also safe: dots
    covered by the receiver's local-fold top entered its block's
    history post-sync through ops its tracking marked (the delta.py
    contract), so the receiver minted its own circulating marks for
    those rows — transitive delivery survives. Masked slots are zeroed
    so the packet stays canonical (and ``bytes_useful`` honest); the
    wire shape is unchanged."""
    covered = jnp.all(pkt.ctxs == pkt.rows, axis=-1) & jnp.all(
        pkt.rows <= digest[None, :], axis=-1
    )
    keep = pkt.valid & ~covered
    return pkt._replace(
        valid=keep,
        rows=jnp.where(keep[:, None], pkt.rows, 0),
        ctxs=jnp.where(keep[:, None], pkt.ctxs, 0),
    )


def close_top_orswot(folded: OrswotState, top: jax.Array) -> OrswotState:
    """Adopt the mesh-wide top and re-replay parked removes under it
    (delta_ring documents why the closure is needed and sound). Shared
    by the plain-orswot and map_orswot delta flavors."""
    ctr = _apply_parked(folded.ctr, folded.dcl, folded.dmask, folded.dvalid)
    still = ~jnp.all(folded.dcl <= top[None, :], axis=-1)
    dvalid = folded.dvalid & still
    return OrswotState(
        top=top,
        ctr=ctr,
        dcl=jnp.where(dvalid[:, None], folded.dcl, 0),
        dmask=folded.dmask & dvalid[:, None],
        dvalid=dvalid,
    )


def mesh_delta_gossip(
    state: OrswotState,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
    local_fold: str = "auto",
    telemetry: bool = False,
    pipeline: bool = True,
    digest: bool = True,
    donate: bool = False,
    faults=None,
    ack_window=False,
    wal=None,
    fused: bool = True,
):
    """Ring δ anti-entropy over the mesh: each device folds its local
    replica block (OR-folding dirty, max-folding contexts), then runs
    ``rounds`` unit-shift ring rounds shipping ONE bounded DeltaPacket
    per link per round instead of a whole state (``mesh_gossip``'s
    bandwidth mode for large, low-churn element universes).

    ``dirty [R, E]`` / ``fctx [R, E, A]`` come from
    ``interval_accumulate`` tracking since the replicas last synced.

    ROUNDS BUDGET — read this before trusting the default: ``rounds`` =
    P-1 (default) guarantees convergence only when ``cap`` covers each
    device's dirty backlog every round. If the backlog exceeds ``cap``,
    residue drains over EXTRA rounds (round-robin, no loss) and each
    forwarding hop needs its own ring latency — budget
    ``(P-1) * (1 + ceil(backlog / cap))`` rounds for a capped drain.
    The returned ``residue`` is the RUNTIME signal for an under-budgeted
    run (``overflow`` flags the parked-remove buffer, not residue, and
    the ``dirty`` mask is noisy with domain-forwarding re-marks):
    ``residue == 0`` proves the budget sufficed, ``> 0`` means re-run
    with more rounds per the formula (delta_ring.run_delta_ring
    documents the indicator's soundness). The cap-independence property
    tests (test_delta*.py) pin the budget formula.

    With ``pipeline=True`` (default) the schedule is double-buffered —
    round r+1's packet ships while round r's merges, hiding the DMA
    behind the merge kernels — at the price of sends one apply stale:
    propagation takes TWO rounds per hop, so the default budget (and
    the certificate window) becomes ``2*(P-1)-1`` rounds and an
    explicit budget tuned for the sequential schedule should roughly
    double. ``pipeline=False`` restores the sequential
    extract→ship→apply rounds (bit-identical HLO to the pre-flag
    program). ``digest=True`` (default) prepends one tiny inverse-ring
    exchange of the frozen receiver tops and masks out packet slots the
    receiver provably already covers (``gate_delta``) — converged
    states stay bit-identical while ``bytes_useful`` drops to
    O(changed); ``donate=True`` consumes (state, dirty) and aliases the
    outputs in place (run_delta_ring documents all three).

    Returns ``(states [P, ...], dirty [P, E], overflow, residue)`` —
    overflow is the deferred-buffer flag, as in ``mesh_gossip``;
    residue the convergence indicator above. ``telemetry=True`` appends
    the in-kernel Telemetry pytree (telemetry.py) as a fifth element.
    ``faults=`` (a ``crdt_tpu.faults.FaultPlan``) injects seeded
    drop/corrupt/delay link faults with a checksum lane on every packet
    and appends a ``FaultCounters`` pytree LAST — lost packets force
    ``residue >= 1`` and suppress the top closure, so degraded rows
    stay valid partial states for state-driven resync
    (delta_ring.run_delta_ring documents the semantics).
    ``ack_window=True`` layers the per-link acked-interval mask over
    the digest gate — the peer's positive confirmations retire
    re-circulated δs INCLUDING removals (crdt_tpu/delta_opt/ackwin.py;
    converged states stay bit-identical, ``bytes_acked_skipped``
    reports the win). ``wal=`` (a ``crdt_tpu.durability.Wal``) logs the
    run's converged rows as one irreducible δ record + round barrier —
    crash recovery then replays snapshot + log suffix
    (run_delta_ring documents the host-side semantics).
    ``fused=True`` (default) ships every packet through the one-pass
    fused wire kernel and bit-packed format (parallel/wire.py —
    converged states bit-identical, collective bytes roughly halved);
    ``fused=False`` traces the byte-identical layered pre-flag
    program (run_delta_ring documents the contract)."""
    from ..ops.pallas_kernels import fold_auto
    from .delta_ring import run_delta_ring

    state = pad_replicas(state, mesh.shape[REPLICA_AXIS])
    state = pad_elements(state, mesh.shape[ELEMENT_AXIS])
    pad_r = state.top.shape[0] - dirty.shape[0]
    pad_e = state.ctr.shape[-2] - dirty.shape[-1]
    if pad_r or pad_e:  # zero-pad copies would defeat donation
        dirty = jnp.pad(dirty, ((0, pad_r), (0, pad_e)))
        fctx = jnp.pad(fctx, ((0, pad_r), (0, pad_e), (0, 0)))

    from ..ops.orswot import changed_members

    return run_delta_ring(
        "delta_gossip", state, dirty, fctx, mesh, rounds, cap,
        specs=orswot_specs(),
        local_fold=partial(fold_auto, prefer=local_fold),
        extract=extract_delta,
        apply_fn=apply_delta,
        close_top=close_top_orswot,
        cache_extra=(local_fold,),
        telemetry=telemetry, slots_fn=changed_members,
        pipeline=pipeline, digest=digest, gate=gate_delta, donate=donate,
        faults=faults, ack_window=ack_window, wal=wal, wal_kind="orswot", fused=fused,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _reg_delta_ep(name, kind, mk_state, n_rows, call):
    """Register a δ-ring entry: (state, dirty, fctx) example args with
    R == P identity batches in the shared gate geometry
    (crdt_tpu.analysis.gate_states — fctx actor lanes = gate_states.GA,
    dtype following the state's clock lanes)."""
    from ..analysis import gate_states as gs
    from ..analysis.registry import register_entry_point

    def make_args(mesh):
        p = gs.replicas(mesh)
        state = mk_state(p)
        dirty = jnp.zeros((p, n_rows), bool)
        # fctx rides the state's clock dtype (the leading leaf is the
        # top clock for every flavor) so a counter_dtype="uint64"
        # config gates the same program production runs.
        clock_dtype = jax.tree.leaves(state)[0].dtype
        fctx = jnp.zeros((p, n_rows, gs.GA), clock_dtype)
        return state, dirty, fctx

    register_entry_point(
        name, kind=kind, make_args=make_args,
        invoke=lambda mesh, args: call(*args, mesh),
        n_donated=2,
        mesh_axes=(REPLICA_AXIS, ELEMENT_AXIS),
    )


def _register():
    from ..analysis import gate_states as gs
    from ..analysis.registry import register_fault_surface

    _reg_delta_ep(
        "mesh_delta_gossip", "delta_gossip", gs.mk_dense, gs.GE,
        lambda s, d, f, mesh: mesh_delta_gossip(
            s, d, f, mesh, local_fold="tree", donate=True
        ),
    )
    register_fault_surface("mesh_delta_gossip", module=__name__)


_register()
