"""crdt_tpu.parallel — the distributed anti-entropy layer.

The reference has no communication backend at all: every type derives
serde and the *caller* ships bytes (SURVEY.md §3 row 17, §3.1). This
package is the TPU-native replacement — the single biggest new piece vs
the reference (SURVEY.md §6.8): replica state lives sharded over a
``jax.sharding.Mesh`` and anti-entropy runs as XLA collectives over
ICI/DCN instead of caller-transported bytes.

Mesh axes (SURVEY.md §3.1 mapping):

- ``replica`` — data-parallel analog: one lane per CRDT replica.
- ``element`` — tensor/sequence-parallel analog: the member universe of
  an ORSWOT (or key space of a Map) sharded across devices.

Collectives provided (all usable inside ``jax.shard_map``):

- :func:`collectives.all_reduce_join` — full-mesh anti-entropy collapsed
  into one all-reduce with the ORSWOT lattice-join monoid (recursive
  doubling over ICI; the north star's ``lax.all_reduce``).
- :func:`collectives.all_reduce_clock` — the same for plain vector
  clocks / counters (``lax.pmax``).
- :func:`collectives.ring_round` — one ``ppermute`` gossip round
  (pairwise anti-entropy; the ring-attention-shaped component).

Top-level entry points (:mod:`.anti_entropy`) wrap these in
``jax.shard_map`` over a mesh and are what models/bench/driver call.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map under jax.experimental with the
    # replication checker flag named check_rep instead of check_vma.
    # Installed before any submodule import so every entry point sees
    # the same ``jax.shard_map`` surface regardless of jax version.
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def _shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=bool(check_vma), **kw,
        )

    _jax.shard_map = _shard_map_compat

from .mesh import (
    REPLICA_AXIS,
    ELEMENT_AXIS,
    make_mesh,
    map_specs,
    map_out_specs,
    map3_specs,
    map_orswot_specs,
    nested_map_specs,
    orswot_specs,
    orswot_out_specs,
    shard_map3,
    shard_map_orswot,
    shard_map_state,
    shard_nested_map,
    shard_orswot,
)
from .collectives import (
    all_reduce_clock,
    all_reduce_join,
    all_reduce_lattice,
    ring_round,
)
from .anti_entropy import (
    gossip_elastic,
    mesh_fold,
    mesh_fold_clocks,
    mesh_fold_gset,
    mesh_fold_lww,
    mesh_fold_map,
    mesh_fold_map3,
    mesh_fold_map_orswot,
    mesh_fold_mvreg,
    mesh_fold_nested_map,
    mesh_fold_sparse,
    mesh_fold_sparse_mvmap,
    mesh_fold_sparse_nested,
    mesh_gossip_sparse_mvmap,
    mesh_gossip_sparse_nested,
    mesh_gossip,
    mesh_gossip_sparse,
    mesh_gossip_map,
    mesh_gossip_map3,
    mesh_gossip_map_orswot,
    mesh_gossip_nested_map,
)
from .sparse_shard import (
    mesh_fold_sparse_map,
    mesh_fold_sparse_mvmap_sharded,
    mesh_fold_sparse_nested_sharded,
    mesh_fold_sparse_sharded,
    split_cells,
    split_nested,
    split_segments,
)
from .stream import (
    StreamFaultReport,
    StreamInterrupted,
    iter_blocks,
    mesh_stream_fold,
    mesh_stream_fold_sparse,
    mesh_stream_fold_sparse_mvmap,
    mesh_stream_fold_sparse_sharded,
)
from .delta_ring import delta_gossip_elastic
from .fanout_push import mesh_fanout_push
from .serve_apply import mesh_serve_apply
from .delta import (
    DeltaPacket,
    apply_delta,
    dirty_between,
    extract_delta,
    interval_accumulate,
    mesh_delta_gossip,
)
from .delta_map import (
    MapDeltaPacket,
    apply_delta_map,
    extract_delta_map,
    interval_accumulate_map,
    mesh_delta_gossip_map,
)
from .delta_map_orswot import (
    MapOrswotDeltaPacket,
    apply_delta_mo,
    extract_delta_mo,
    interval_accumulate_mo,
    mesh_delta_gossip_map_orswot,
)
from .delta_map3 import (
    Map3DeltaPacket,
    apply_delta_m3,
    extract_delta_m3,
    interval_accumulate_m3,
    mesh_delta_gossip_map3,
)
from . import multihost

__all__ = [
    "multihost",
    "delta_gossip_elastic",
    "gossip_elastic",
    "StreamFaultReport",
    "StreamInterrupted",
    "iter_blocks",
    "mesh_stream_fold",
    "mesh_stream_fold_sparse",
    "mesh_stream_fold_sparse_mvmap",
    "mesh_stream_fold_sparse_sharded",
    "mesh_fanout_push",
    "mesh_serve_apply",
    "DeltaPacket",
    "apply_delta",
    "dirty_between",
    "interval_accumulate",
    "MapDeltaPacket",
    "apply_delta_map",
    "extract_delta_map",
    "interval_accumulate_map",
    "mesh_delta_gossip_map",
    "MapOrswotDeltaPacket",
    "apply_delta_mo",
    "extract_delta_mo",
    "interval_accumulate_mo",
    "mesh_delta_gossip_map_orswot",
    "Map3DeltaPacket",
    "apply_delta_m3",
    "extract_delta_m3",
    "interval_accumulate_m3",
    "mesh_delta_gossip_map3",
    "extract_delta",
    "mesh_delta_gossip",
    "map3_specs",
    "map_orswot_specs",
    "nested_map_specs",
    "shard_map3",
    "shard_map_orswot",
    "shard_nested_map",
    "mesh_fold_map3",
    "mesh_fold_map_orswot",
    "mesh_fold_nested_map",
    "mesh_fold_gset",
    "mesh_fold_lww",
    "mesh_fold_mvreg",
    "mesh_fold_sparse_map",
    "mesh_fold_sparse_mvmap",
    "mesh_fold_sparse_mvmap_sharded",
    "mesh_fold_sparse_nested_sharded",
    "mesh_fold_sparse_nested",
    "mesh_gossip_sparse_mvmap",
    "mesh_gossip_sparse_nested",
    "mesh_fold_sparse_sharded",
    "split_cells",
    "split_nested",
    "split_segments",
    "mesh_gossip_map",
    "mesh_gossip_sparse",
    "mesh_gossip_map3",
    "mesh_gossip_map_orswot",
    "mesh_gossip_nested_map",
    "REPLICA_AXIS",
    "ELEMENT_AXIS",
    "make_mesh",
    "map_specs",
    "map_out_specs",
    "orswot_specs",
    "orswot_out_specs",
    "shard_map_state",
    "shard_orswot",
    "all_reduce_join",
    "all_reduce_clock",
    "all_reduce_lattice",
    "ring_round",
    "mesh_fold",
    "mesh_fold_clocks",
    "mesh_fold_map",
    "mesh_gossip",
]
