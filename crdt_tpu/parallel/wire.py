"""The bit-packed δ wire format + the fused send path.

PR 12's δ ring shipped every packet as its in-memory pytree: bool
presence planes at one BYTE per lane, slot indices as i32, and every
clock plane at full counter width — then made five separate
elementwise passes over those planes (digest gate, ack mask, checksum,
fault walk, telemetry counts) before the ``ppermute``. This module
replaces both halves of that: :class:`WireCodec` lowers a flavor's
``DeltaPacket``-family pytree onto a compact all-u32 wire tree in ONE
fused pass (:func:`crdt_tpu.ops.wire_kernels.wire_pack` — gate ∧ mask
∧ encode ∧ checksum ∧ count in a single read of the lanes), and the
receiver inverts it with one plain-lax pass XLA fuses into the apply.

Wire layout (``WirePacket`` — every leaf u32, leaf order static):

    slots  [C, Ws]   clock lanes of the slot planes, delta-encoded
                     against the link watermark as biased u16 pairs
                     (two lanes per word, half-split pairing)
    parked [ΣD, Wp]  parked-remove clock lanes, same encoding against
                     the digest watermark
    ids    [⌈ni/2⌉]  slot indices + actor ids as u16 pairs (their
                     static bounds — E, A ≤ 2^16 — prove the
                     narrowing lossless; wider universes ship raw)
    raws   [nr]      unbounded non-clock lanes (map payload ids),
                     bitcast
    bits   [⌈nb/32⌉] EVERY bool plane of the packet — slot validity,
                     content masks, parked dmask/dkeys/dvalid — as one
                     u32 bitmap (8× the bool planes' wire density)

**Watermark encoding.** A clock lane ships as
``(value - base) + 32768`` in u16 — exact for values within ±32 Ki of
``base`` — where ``base`` is the link's acked watermark
(``delta_opt/ackwin.py`` window ctx, mirrored receiver-side, see
below) joined with the receiver's frozen digest top when ``digest=``
is on, and zero with both off. Both ends derive the base from
knowledge they provably share, so the round-trip is bit-exact.

**Soundness of the narrow window.** A slot whose lanes fall outside
the ±32 Ki window is DEFERRED: it ships invalid, the ring re-marks its
row dirty BEFORE the round's backlog count, and the residue
certificate counts the starvation — an unencodable slot can therefore
never be silently lost, it only keeps the run uncertified (the same
one-sided-indicator contract as a too-small ``cap``). A parked-remove
slot that cannot encode is stricter: removal knowledge must never go
quietly missing (the PR 3 wider-gate lesson), so the sender counts it
as WIRE LOSS — residue is forced ≥ 1 and the final top-closure
adoption is suppressed exactly as for a lossy faulted link
(``delta_ring.py``). In steady state clocks cluster within the window
of their link watermark, so deferral is the exception the certificate
prices, not the path.

**Receiver-side ack mirror.** The sender's ack window
(``ackwin.AckWindow``) is promoted from bits the RECEIVER itself
computed and shipped, so the receiver can maintain a bit-identical
mirror of the window's ctx plane from its own applies
(:func:`mirror_promote`) — under ``pipeline=True`` the mirror decodes
one promotion LATE (the sender encodes round r+1's packet before
absorbing round r's acks), so the ring carries the previous mirror
alongside the current one. That lockstep is what lets the acked
watermark serve as the delta-encoding base in both directions.

The checksum lane (``faults=``) is computed over the PACKED wire —
:func:`wire_checksum` chains the kernel's in-pass partials with the
small host-side leaves to a digest bit-equal to
``faults.integrity.checksum`` of the wire tree, so the receiver
verifies with the stock integrity path and detection semantics are
unchanged.

``fused=False`` on the δ entries bypasses this module entirely and
traces the byte-identical PR 12-era program (HLO-pinned in
tests/test_wire.py); :class:`WireKey` marks fused-off jit-cache
entries so the analysis gates never read a stale program (the PR 8/9
cache-poisoning class).
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..delta_opt.ackwin import AckWindow, _content_names, _core
from ..ops import wire_kernels as wk

_MIX = 0x9E3779B1  # integrity.checksum's leaf-chaining constant
_U16_SPAN = 65536


class WireKey(NamedTuple):
    """The jit-cache marker for FUSED-OFF ring programs: a fused=False
    run traces the legacy layered wire, which must never be the
    program the analysis gates (aliasing/cost/jit-lint) read back for
    the default entry — ``analysis.jit_lint._cached_entry_fn`` skips
    cache entries carrying this marker exactly as it skips FaultPlan /
    AckWindowKey keys (the PR 8/9 poisoning class, pinned by
    tests/test_wire.py)."""

    fused: bool = False


class WirePacket(NamedTuple):
    """The all-u32 wire tree (module docstring layout). Fields hold
    tuples so flavors without a plane class contribute no leaf; the
    first leaf is always the slot clock matrix — the fault injector's
    perturbation target, covered by the checksum lane like every
    other leaf."""

    slots: Tuple[jax.Array, ...]
    parked: Tuple[jax.Array, ...]
    ids: Tuple[jax.Array, ...]
    raws: Tuple[jax.Array, ...]
    bits: Tuple[jax.Array, ...]


class WireAux(NamedTuple):
    """Sender-side byproducts of one fused pack (all derived in the
    kernel's single read of the lanes)."""

    keep: jax.Array         # [C] bool — slots on the wire
    defer: jax.Array        # [C] bool — narrow-deferred (re-mark dirty)
    covered: jax.Array      # [C] bool — ack verdicts (skip-byte unit)
    parked_lost: jax.Array  # i32 — unencodable parked slots (residue)
    packed_words: jax.Array # u32 — nonzero wire words (packed bytes)
    checksum: jax.Array     # u32 — integrity digest of the wire tree


# Leaf classes, decided by field name + shape — the packet conventions
# every delta flavor shares (delta.py DeltaPacket, delta_map.py
# MapDeltaPacket, the nested_delta wrappers).
(_CLOCK, _CTX, _PDCL, _ID, _RAW, _SLOTVALID, _CBOOL, _PVALID,
 _PBOOL) = range(9)

_PARKED_SUFFIXES = ("dcl", "dmask", "dkeys", "dvalid")


def _classify(name: str, shape, dtype) -> int:
    if name == "idx" or name == "wact":
        return _ID
    if name == "ctxs":
        return _CTX
    if name.endswith("dcl"):
        return _PDCL
    if name.endswith("dvalid"):
        return _PVALID
    if name.endswith("dmask") or name.endswith("dkeys"):
        return _PBOOL
    if dtype == jnp.bool_:
        return _SLOTVALID if len(shape) == 1 else _CBOOL
    if name in ("rows", "wctr", "clk"):
        return _CLOCK
    return _RAW


def _named_leaves(tree, out=None):
    """Depth-first (NamedTuple field order — jax's flatten order) list
    of ``(field name, leaf)``: the static walk both ends share."""
    if out is None:
        out = []
    for f in tree._fields:
        child = getattr(tree, f)
        if hasattr(child, "_fields"):
            _named_leaves(child, out)
        else:
            out.append((f, child))
    return out


class _Rec(NamedTuple):
    """One packet leaf's static plan row."""

    i: int          # flat leaf index
    name: str
    cls: int
    shape: Tuple[int, ...]
    dtype: object


class WireCodec:
    """The static pack/unpack plan for one flavor's packet template.

    Built INSIDE the traced ring from ``jax.eval_shape`` of the
    flavor's extract — every decision is shape/dtype/name-static, so
    sender and receiver derive the identical plan. ``know_fn`` maps
    the packet to its per-slot knowledge clock ``[C, A]`` (the
    digest-gate subject: dense rows, map ``_key_knowledge``)."""

    def __init__(self, template, n_rows: int, know_fn: Callable,
                 gated: bool, acked: bool,
                 interpret: Optional[bool] = None):
        self.treedef = jax.tree.structure(template)
        self.n_rows = n_rows
        self.know_fn = know_fn
        self.gated = gated
        self.acked = acked
        self.interpret = interpret
        core = _core(template)
        self.c = core.idx.shape[0]
        self.a = core.ctxs.shape[-1]
        self.ct = core.ctxs.dtype
        self.content_names = _content_names(core)

        named = _named_leaves(template)
        assert len(named) == len(jax.tree.leaves(template))
        self.records: List[_Rec] = [
            _Rec(i, name, _classify(name, tuple(leaf.shape), leaf.dtype),
                 tuple(leaf.shape), leaf.dtype)
            for i, (name, leaf) in enumerate(named)
        ]

        def size(r):
            n = 1
            for s in r.shape:
                n *= s
            return n

        self._size = size
        by_cls = lambda *cls: [r for r in self.records if r.cls in cls]
        self.clock_recs = by_cls(_CLOCK)
        self.ctx_rec = by_cls(_CTX)[0]
        self.id_recs = by_cls(_ID)
        self.raw_recs = by_cls(_RAW)
        self.bool_recs = by_cls(_SLOTVALID, _CBOOL, _PVALID, _PBOOL)
        self.parked_recs = by_cls(_PDCL)
        self.pvalid_recs = by_cls(_PVALID)

        # Slot clock matrix columns: content planes in walk order, the
        # ctx plane LAST (the kernel's [ctx_lo, ctx_hi) range).
        cols = 0
        self.clock_cols: List[Tuple[int, int]] = []
        for r in self.clock_recs:
            n = size(r) // self.c
            self.clock_cols.append((cols, cols + n))
            cols += n
        self.ctx_lo, self.ctx_hi = cols, cols + self.a
        self.lc = cols + self.a

        # Parked groups: (prefix, D, row offset in the concatenated
        # parked matrix) in walk order — ``dcl``-suffixed leaves and
        # their ``dvalid`` masks pair by prefix.
        self.pd = 0
        self.pgroup_row = {}
        for r in self.parked_recs:
            pref = r.name[: -len("dcl")]
            self.pgroup_row[pref] = self.pd
            self.pd += r.shape[0]

        self.n_bits = sum(size(r) for r in self.bool_recs)
        # u16 ids need their static bounds proven: slot indices by the
        # row universe, actor ids by the clock width. A wider universe
        # ships ids raw — the narrowing is a proof, not a hope.
        self.narrow_ids = (n_rows <= _U16_SPAN and self.a <= _U16_SPAN)
        self.slot_spec = wk.WireLaneSpec(
            lc=self.lc, ctx_lo=self.ctx_lo, ctx_hi=self.ctx_hi,
            gated=gated, acked=acked,
        )
        self.parked_spec = wk.WireLaneSpec(lc=self.a, parked=True)

        # Static byte prices replicating telemetry.packet_useful_bytes'
        # group arithmetic, so the fused path reports the identical
        # bytes_useful quantity without materializing the gated packet.
        parked_cls = (_PDCL, _PVALID, _PBOOL)
        self.slot_price = sum(
            (size(r) // self.c) * jnp.dtype(r.dtype).itemsize
            for r in self.records if r.cls not in parked_cls
        )
        self.parked_prices = {}
        for pref, _row in self.pgroup_row.items():
            group = [
                r for r in self.records
                if r.name in tuple(pref + s for s in _PARKED_SUFFIXES)
            ]
            d = group[0].shape[0]
            self.parked_prices[pref] = (d, sum(
                (size(r) // d) * jnp.dtype(r.dtype).itemsize
                for r in group
            ))

    # ---- shared base/watermark derivation --------------------------------

    def _slot_base(self, idx, rtop, mctx):
        """The per-slot watermark ``[C, A]``: acked-window ctx (when
        on) joined with the digest top (when gated), zero otherwise —
        knowledge both ends provably share."""
        base = jnp.zeros((idx.shape[0], self.a), self.ct)
        if self.gated and rtop is not None:
            base = jnp.maximum(base, rtop[None, :].astype(self.ct))
        if self.acked and mctx is not None:
            base = jnp.maximum(
                base, jnp.take(mctx, idx, axis=0).astype(self.ct)
            )
        return base

    def _base_matrix(self, basemat, wact2):
        """Per-lane bases in the slot matrix's column layout:
        ``basemat [C, A]`` broadcast per A-minor plane, gathered at
        the actor id for witness-counter lanes, zero for anything
        else — a deterministic rule both ends compute."""
        bases = []
        for r, (lo, hi) in zip(self.clock_recs, self.clock_cols):
            n = hi - lo
            if r.name == "wctr" and wact2 is not None:
                bases.append(jnp.take_along_axis(
                    basemat, wact2.astype(jnp.int32), axis=-1
                ))
            elif n == self.a:
                bases.append(basemat)
            elif n % self.a == 0:
                bases.append(jnp.tile(basemat, (1, n // self.a)))
            else:
                bases.append(jnp.zeros((self.c, n), self.ct))
        bases.append(basemat)  # ctx columns
        return jnp.concatenate(bases, axis=-1)

    def _parked_base(self, rtop):
        if self.gated and rtop is not None:
            return jnp.broadcast_to(
                rtop[None, :].astype(self.ct), (self.pd, self.a)
            )
        return jnp.zeros((self.pd, self.a), self.ct)

    # ---- sender ----------------------------------------------------------

    def pack(self, pkt, rtop=None, win: Optional[AckWindow] = None,
             win_ctx=None) -> Tuple[WirePacket, WireAux]:
        """One fused pass from the flavor packet to the wire tree.
        ``rtop`` is the receiver's frozen digest top (``digest=``),
        ``win`` the link's ack window (``ack_window=``) whose ctx
        plane doubles as the encode watermark (``win_ctx`` overrides
        it where the pipelined schedule needs the lagged state)."""
        leaves = jax.tree.leaves(pkt)
        core = _core(pkt)
        idx = core.idx
        wact2 = None
        for r in self.id_recs:
            if r.name == "wact":
                wact2 = leaves[r.i].reshape(self.c, -1)
        mctx = (win.ctx if win_ctx is None else win_ctx) if (
            win is not None
        ) else None
        basemat = self._slot_base(idx, rtop, mctx)
        clocks = jnp.concatenate(
            [leaves[r.i].reshape(self.c, hi - lo)
             for r, (lo, hi) in zip(self.clock_recs, self.clock_cols)]
            + [leaves[self.ctx_rec.i].reshape(self.c, self.a)],
            axis=-1,
        ).astype(self.ct)
        base = self._base_matrix(basemat, wact2)

        know = dig = winc = ack_ok = None
        if self.gated:
            know = self.know_fn(pkt).astype(self.ct)
            dig = jnp.broadcast_to(
                rtop[None, :].astype(self.ct), (self.c, self.a)
            )
        if self.acked:
            winc, same_rest = self._win_matrix(win, idx, leaves)
            ack_ok = jnp.take(win.ackd, idx) & same_rest
        out = wk.wire_pack(
            self.slot_spec, clocks, base, core.valid,
            know=know, dig=dig, winc=winc, ack_ok=ack_ok,
            interpret=self.interpret,
        )

        # Parked clock planes: one fused pass over the concatenated
        # levels against the digest watermark; an unencodable VALID
        # slot is wire loss (module docstring).
        pcl = jnp.concatenate([
            leaves[r.i].reshape(-1, self.a) for r in self.parked_recs
        ]).astype(self.ct)
        pvalid = jnp.concatenate([leaves[r.i] for r in self.pvalid_recs])
        pout = wk.wire_pack(
            self.parked_spec, pcl, self._parked_base(rtop), pvalid,
            interpret=self.interpret,
        )
        pvalid_wire = pvalid & ~pout.defer

        # ids / raws / bools — tiny planes, XLA fuses them around the
        # kernel calls.
        def slotmask(flat):
            return jnp.where(
                jnp.repeat(out.keep, flat.shape[0] // self.c), flat,
                jnp.zeros_like(flat),
            )

        ids = []
        for r in self.id_recs:
            flat = leaves[r.i].reshape(-1)
            # Masked/invalid slots ship ZERO id lanes too (the packed
            # wire stays mostly-zero on quiet workloads); the receiver
            # reconstructs distinct no-op filler indices for them
            # (:func:`fill_invalid_idx` — provably no-op scatter
            # targets, so converged states stay bit-identical).
            flat = slotmask(flat)
            ids.append(
                wk.pack_u16_pairs(flat) if self.narrow_ids
                else flat.astype(jnp.uint32)
            )
        raws = [
            jax.lax.bitcast_convert_type(
                slotmask(leaves[r.i].reshape(-1)), jnp.uint32
            )
            for r in self.raw_recs
        ]
        bools = []
        for r in self.bool_recs:
            b = leaves[r.i]
            if r.cls == _SLOTVALID:
                b = out.keep
            elif r.cls == _CBOOL:
                b = slotmask(b.reshape(-1))
            elif r.cls == _PVALID:
                lo = self.pgroup_row[r.name[: -len("dvalid")]]
                b = pvalid_wire[lo:lo + r.shape[0]]
            else:  # _PBOOL: zero rows whose parked slot left the wire
                pref = (r.name[: -len("dmask")]
                        if r.name.endswith("dmask")
                        else r.name[: -len("dkeys")])
                lo = self.pgroup_row[pref]
                sel = pvalid_wire[lo:lo + r.shape[0]]
                b = (b & sel.reshape((r.shape[0],) + (1,) * (b.ndim - 1))
                     ).reshape(-1)
            bools.append(b.reshape(-1))
        bits = wk.pack_bits(jnp.concatenate(bools))

        wire = WirePacket(
            slots=(out.words,),
            parked=(pout.words,),
            ids=tuple(ids),
            raws=tuple(raws),
            bits=(bits,),
        )
        nnz = out.nnz + pout.nnz
        for x in list(ids) + raws + [bits]:
            nnz = nnz + jnp.sum(
                (x != 0).astype(jnp.uint32), dtype=jnp.uint32
            )
        aux = WireAux(
            keep=out.keep, defer=out.defer, covered=out.covered,
            parked_lost=jnp.sum(
                (pvalid & pout.defer).astype(jnp.int32), dtype=jnp.int32
            ),
            packed_words=nnz,
            checksum=wire_checksum(wire, {0: out.chk, 1: pout.chk}),
        )
        return wire, aux

    def _win_matrix(self, win: AckWindow, idx, leaves):
        """The ack comparison inputs: the window's confirmed content
        planes gathered at ``idx`` in the clock columns + its ctx in
        the ctx columns (the in-kernel half of ``gate_window``'s
        verdict), and the one-bool-per-slot equality of the NON-clock
        content lanes (ids, payload, content bools — tiny, compared
        here)."""
        core_t = _core(jax.tree.unflatten(
            self.treedef,
            [jax.ShapeDtypeStruct(r.shape, r.dtype) for r in self.records],
        ))
        by_name = {}
        for f, rows_tree in zip(self.content_names, win.rows):
            node = getattr(core_t, f)
            if hasattr(node, "_fields"):
                for (n, _), v in zip(
                    _named_leaves(node), jax.tree.leaves(rows_tree)
                ):
                    by_name.setdefault(n, []).append(v)
            else:
                by_name.setdefault(f, []).append(rows_tree)
        gath = lambda v: jnp.take(v, idx, axis=0)
        cols = [
            gath(by_name[r.name].pop(0)).reshape(self.c, hi - lo)
            for r, (lo, hi) in zip(self.clock_recs, self.clock_cols)
        ]
        cols.append(gath(win.ctx))
        winc = jnp.concatenate(cols, axis=-1).astype(self.ct)
        same = jnp.ones((self.c,), bool)
        for r in self.records:
            vals = by_name.get(r.name)
            if not vals or r.cls in (_CLOCK, _CTX):
                continue
            w = gath(vals.pop(0)).reshape(self.c, -1)
            p = leaves[r.i].reshape(self.c, -1)
            same = same & jnp.all(w == p, axis=-1)
        return winc, same

    # ---- receiver --------------------------------------------------------

    def unpack(self, wire: WirePacket, own_top=None, mirror_ctx=None):
        """Invert :meth:`pack` with the receiver's copy of the
        watermark: its OWN frozen top (≡ the digest the sender held)
        and its ack-window mirror ctx (≡ the sender's window at
        encode time — module docstring lag discipline). Returns the
        flavor packet, bit-identical to the sender's gated/masked
        packet."""
        leaves = [None] * len(self.records)
        # bools first — slot validity selects the clock decode AND the
        # invalid-slot index reconstruction.
        bit_flat = wk.unpack_bits(wire.bits[0], self.n_bits)
        off = 0
        keep = None
        pvalid_parts = []
        for r in self.bool_recs:
            n = self._size(r)
            b = bit_flat[off:off + n].reshape(r.shape)
            off += n
            leaves[r.i] = b
            if r.cls == _SLOTVALID:
                keep = b
            if r.cls == _PVALID:
                pvalid_parts.append(b)
        # ids next — clock bases may gather at actor ids; invalid
        # slots' indices (shipped zero) become distinct no-op fillers.
        wact2 = None
        for k, r in enumerate(self.id_recs):
            w = wire.ids[k]
            flat = (
                wk.unpack_u16_pairs(w, self._size(r), r.dtype)
                if self.narrow_ids else w.astype(r.dtype)
            )
            leaves[r.i] = flat.reshape(r.shape)
            if r.name == "idx":
                leaves[r.i] = fill_invalid_idx(
                    leaves[r.i], keep, self.n_rows
                )
            if r.name == "wact":
                wact2 = leaves[r.i].reshape(self.c, -1)
        # raws.
        for k, r in enumerate(self.raw_recs):
            leaves[r.i] = jax.lax.bitcast_convert_type(
                wire.raws[k], r.dtype
            ).reshape(r.shape)
        # slot clocks under the shared watermark.
        idx = leaves[self.id_recs[0].i]  # idx walks first by convention
        basemat = self._slot_base(idx, own_top, mirror_ctx)
        base = self._base_matrix(basemat, wact2)
        dec = wk.wire_unpack(
            self.slot_spec, wire.slots[0], base, keep, self.ct
        )
        for r, (lo, hi) in zip(self.clock_recs, self.clock_cols):
            leaves[r.i] = dec[:, lo:hi].reshape(r.shape).astype(r.dtype)
        rc = self.ctx_rec
        leaves[rc.i] = dec[:, self.ctx_lo:self.ctx_hi].reshape(
            rc.shape
        ).astype(rc.dtype)
        # parked clocks.
        pdec = wk.wire_unpack(
            self.parked_spec, wire.parked[0], self._parked_base(own_top),
            jnp.concatenate(pvalid_parts), self.ct,
        )
        lo = 0
        for r in self.parked_recs:
            d = r.shape[0]
            leaves[r.i] = pdec[lo:lo + d].reshape(r.shape).astype(r.dtype)
            lo += d
        return jax.tree.unflatten(self.treedef, leaves)

    # ---- sender-side bookkeeping ----------------------------------------

    def mask(self, pkt, keep):
        """The sender's gated packet (content zeroed where the fused
        pass masked or deferred) — the ack window's ``sent``
        bookkeeping copy, NOT a wire pass."""
        leaves = list(jax.tree.leaves(pkt))
        for r in self.records:
            if r.cls in (_PDCL, _PVALID, _PBOOL) or r.name == "idx":
                continue
            if r.cls == _SLOTVALID:
                leaves[r.i] = keep
                continue
            sel = keep.reshape((self.c,) + (1,) * (len(r.shape) - 1))
            leaves[r.i] = jnp.where(
                sel, leaves[r.i], jnp.zeros_like(leaves[r.i])
            )
        return jax.tree.unflatten(self.treedef, leaves)

    def useful_bytes(self, pkt, keep) -> jax.Array:
        """``telemetry.packet_useful_bytes`` of the gated packet,
        computed from the keep mask and the static prices (identical
        float32 arithmetic — the fused path's DATA-PACKET
        ``bytes_useful`` stays bit-comparable with the layered
        path's; acked runs additionally count their ack lane at its
        own wire price, bitmap here vs bool plane there, so
        whole-run totals differ by the lane-format delta)."""
        leaves = jax.tree.leaves(pkt)
        total = jnp.sum(keep, dtype=jnp.float32) * self.slot_price
        for r in self.pvalid_recs:
            _, price = self.parked_prices[r.name[: -len("dvalid")]]
            total = total + jnp.sum(leaves[r.i], dtype=jnp.float32) * price
        return total


def wire_checksum(wire: WirePacket, partials) -> jax.Array:
    """``faults.integrity.checksum`` of the wire tree, with the kernel
    in-pass partials standing in for the big leaves (``partials`` maps
    leaf index -> precomputed position-weighted sum): same leaf walk,
    same odd-constant chaining, bit-equal by construction — the
    receiver verifies with the stock integrity lane
    (tests/test_wire.py pins the equality)."""
    total = jnp.zeros((), jnp.uint32)
    for i, leaf in enumerate(jax.tree.leaves(wire)):
        part = partials.get(i)
        if part is None:
            part = wk.leaf_checksum(leaf)
        total = total * jnp.uint32(_MIX) + part
    return total


def fill_invalid_idx(idx, keep, e: int):
    """Distinct no-op scatter targets for the invalid slots whose
    indices shipped as zeros: the first free (un-kept) element
    positions, ascending. An invalid slot's whole apply path is a
    no-op at ANY row (its rows write the gathered receiver values
    back), so only DISTINCTNESS matters — duplicate scatter indices
    with different values would make the apply's writes
    order-dependent. Deterministic on both ends by construction."""
    taken = jnp.zeros((e,), jnp.int32).at[idx].add(
        keep.astype(jnp.int32)
    ) > 0
    free = jnp.argsort(taken, stable=True).astype(idx.dtype)  # free first
    rank = jnp.cumsum(~keep) - 1
    return jnp.where(keep, idx, free[rank])


def core_idx(pkt):
    """The leaf slot packet's element indices (wrapper packets nest —
    the ackwin walk convention)."""
    return _core(pkt).idx


def remark_deferred(dirty, idx, defer):
    """Re-mark narrow-deferred slots dirty (they never reached the
    wire); the ring runs this BEFORE the round's backlog count so a
    perpetually deferred slot keeps the residue certificate honest."""
    return dirty.at[idx].set(jnp.take(dirty, idx) | defer)


def mirror_promote(mctx, pkt, bits, keep):
    """The receiver-side twin of ``ackwin.update_window``'s ctx
    promotion, driven by knowledge the receiver provably holds: the
    packet it just applied and the ack bits it itself computed. Keeps
    the mirror bit-identical to the sender's window ctx — the encode
    watermark's other half."""
    core = _core(pkt)
    ok = core.valid & bits & keep
    old = jnp.take(mctx, core.idx, axis=0)
    return mctx.at[core.idx].set(
        jnp.where(ok[:, None], jnp.maximum(old, core.ctxs), old)
    )


# ---- flavor know functions -------------------------------------------------

def know_dense(pkt):
    """delta.gate_delta's subject: the slot's live dot rows (shared by
    the orswot-core nested flavors, whose gates lift the dense one)."""
    return _core(pkt).rows


def know_map(pkt):
    """delta_map.gate_delta_map's subject: the witness-dot knowledge
    of the shipped content slots."""
    from .delta_map import _key_knowledge

    return _key_knowledge(_core(pkt).child)


# ---- static-analysis registration (crdt_tpu.analysis) ----------------------
# One fused wire kernel FAMILY, one registered surface per δ flavor
# instantiation — the coverage contract the `wire` section of
# tools/run_static_checks.py enforces (a δ ring kind without a
# registered wire surface fails discovery there).

WIRE_SURFACES = {
    "delta_gossip": know_dense,
    "map_delta_gossip": know_map,
    "map_orswot_delta_gossip": know_dense,
    "map3_delta_gossip": know_dense,
}


def _register():
    from ..analysis.registry import register_wire_surface

    for kind in WIRE_SURFACES:
        register_wire_surface(kind, module=__name__)


_register()


__all__ = [
    "WIRE_SURFACES", "WireAux", "WireCodec", "WireKey", "WirePacket",
    "know_dense", "know_map", "mirror_promote", "remark_deferred",
    "wire_checksum",
]
