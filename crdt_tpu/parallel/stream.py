"""Replica-streaming fold — anti-entropy for populations that do not fit.

Every fold entry point so far assumes the whole replica batch is
co-resident in device memory, which caps the population at whatever
``[R, ...]`` HBM holds. The flagship shape (BASELINE's metric of
record: 10,240 replicas x 1M elements) and the δ-CRDT literature's
setting (Almeida et al. 1603.01529; Enes et al. 1803.02750 — replicas
far outnumber any single machine) both need the opposite: an
**arbitrary-N** population streamed through the mesh in device-sized
blocks. This module is that driver:

    acc = identity
    for block in blocks:              # [B, ...] replica blocks
        acc = join(acc, mesh_fold(block))

with three performance disciplines carried over from the ring family:

- **donation** (``donate=True``, default): the per-block step jits with
  ``donate_argnums=(0,)``, so the running accumulator's output aliases
  its input buffers in place (``input_output_alias`` — the PR 3
  zero-copy discipline, gated by tools/check_aliasing.py via the entry
  registry). The stream holds ONE accumulator in HBM, ever.
- **double buffering** (``pipeline=True``, default): block k+1 is
  staged (``jax.device_put`` under async dispatch) right after block
  k's step is dispatched, so the upload DMA overlaps the join kernels —
  the host-loop analog of the δ-ring's ``pipeline=`` loop-edge
  ppermute. ``stream.overlap_hit`` counts stagings issued while the
  previous join was still in flight; ``pipeline=False`` syncs between
  blocks (and the counter stays 0).
- **bounded residency**: peak device-resident replica state is two
  blocks plus the accumulator, independent of N —
  ``stream.staged_bytes`` totals what was staged so the bench can
  report the co-resident-vs-streamed ratio honestly.

Composition hooks:

- ``widen_policy=`` (an :class:`crdt_tpu.elastic.ElasticPolicy`) turns
  on the PR 1 overflow→widen→resume loop **mid-stream**: a block whose
  join overflows the accumulator's capacity discards that step (the
  join is idempotent; the pre-step accumulator is snapshotted exactly
  like ``gossip_elastic``), widens the implicated axes on the
  accumulator and the staged block, and retries. Subsequent blocks are
  widened at staging to the grown caps. Engaging the policy makes the
  loop check flags per block (a host sync) — the price of recovery.
- ``frontier=`` + ``compact_every=`` run the PR 5 causal-stability
  compactor on the accumulator every k blocks, so its parked-remove
  footprint stays bounded over long streams. SAFETY: the frontier must
  be stable over the WHOLE population (reclaim.host_frontier /
  stable_frontier over every replica, streamed or not) — a frontier
  derived only from already-seen blocks could retire a parked remove an
  unseen straggler still needs. ``frontier=None`` with
  ``compact_every`` set compacts against the all-zeros frontier:
  nothing retires, but stale payload scrubs and lanes repack.

Fault containment: a block that fails to stage (source iterator raise,
host OOM, a bad shard) raises :class:`StreamInterrupted` carrying the
accumulator — by construction the exact join of blocks ``[0, k)`` and a
valid lattice state — plus the resume index; re-entering with
``init=exc.acc`` and the remaining blocks completes the fold
bit-identically (tests/test_fault_injection.py pins this).

Block contract: blocks are ``[B, ...]`` batches of one kind (sparse /
dense ORSWOT, sparse Map<K, MVReg>, or element-sharded sparse
``[B, S, ...]`` from ``sparse_shard.split_segments``). The first block
fixes the template; later blocks may be SMALLER (identity-padded — the
ragged tail) or CARRY NARROWER CAPS (widened at staging); both repacks
fall back to a staged copy outside the zero-copy path and count
``stream.unaliasable_blocks``. Blocks larger than the template refuse
(re-chunk the source instead).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry as tele
from ..utils.metrics import metrics, observe_depth, state_nbytes
from .anti_entropy import _cached, _exchange_count, _tel_reduced
from .collectives import all_reduce_lattice
from .mesh import ELEMENT_AXIS, REPLICA_AXIS


@dataclass
class StreamFaultReport:
    """One faulted stream run's accounting (``faults=`` on the stream
    entries): which block indices (in THIS call's delivery order) were
    lost to an injected upload drop, and which arrived corrupted and
    were REJECTED by the in-kernel checksum verify (faults/integrity.py
    — corrupted content is never joined). The accumulator is the exact
    join of the non-lost blocks, so healing is a re-stream:
    ``mesh_stream_fold*(lost_blocks, mesh, init=acc)`` with the faults
    off (the δ-literature's eventual-resync contract)."""

    dropped_blocks: list
    rejected_blocks: list

    @property
    def lost_blocks(self) -> list:
        return sorted(set(self.dropped_blocks) | set(self.rejected_blocks))


class StreamInterrupted(RuntimeError):
    """A block failed to stage mid-stream. ``acc`` is the accumulator —
    the exact lattice join of the non-lost blocks already applied, a
    valid joinable state — and the stream resumes from block
    ``blocks_done`` via ``init=exc.acc`` on a fresh call. ``telemetry``
    carries the partial Telemetry pytree when the run requested one;
    ``fault_report`` the partial :class:`StreamFaultReport` when the
    run injected faults — an interrupted faulted stream must still name
    the blocks already lost BEFORE the interrupt, or the resume
    contract would silently drop them from the final join."""

    def __init__(self, cause: BaseException, acc, blocks_done: int,
                 telemetry=None, fault_report=None):
        super().__init__(
            f"replica stream interrupted at block {blocks_done} "
            f"({type(cause).__name__}: {cause}); .acc holds the join of "
            f"blocks [0, {blocks_done}) — resume with init=exc.acc"
        )
        self.cause = cause
        self.acc = acc
        self.blocks_done = blocks_done
        self.telemetry = telemetry
        self.fault_report = fault_report


@dataclass(frozen=True)
class _StreamPlan:
    """The per-kind closure set the generic block loop composes."""

    kind: str                      # jit-cache kind head
    join_fn: Callable              # (a, b) -> (state, flags)
    fold_fn: Callable              # [rows, ...] -> (state, flags)
    caps_of: Callable              # unbatched state -> {axis: cap}
    empty: Callable                # (caps, batch) -> identity batch
    widen_state: Callable          # (state, {axis: cap}) -> state
    flag_axes: Tuple[str, ...]     # overflow lane -> elastic axis ("" =
                                   #   lane not recoverable by widening)
    slots_fn: Optional[Callable] = None
    compact_fn: Optional[Callable] = None  # (state, frontier) -> (s, n, b)
    sum_axes: Optional[tuple] = None       # slots psum axes (None = done)
    sharded: bool = False          # blocks [B, S, ...], acc [S, ...]


# ---- per-kind plans -------------------------------------------------------

def _plan_sparse() -> _StreamPlan:
    from ..ops import sparse_orswot as sp

    return _StreamPlan(
        kind="sparse_stream_fold",
        join_fn=sp.join,
        fold_fn=sp.fold,
        caps_of=lambda s: {
            "dot_cap": s.eid.shape[-1], "n_actors": s.top.shape[-1],
            "deferred_cap": s.didx.shape[-2], "rm_width": s.didx.shape[-1],
        },
        empty=lambda caps, batch: sp.empty(
            caps["dot_cap"], caps["n_actors"], caps["deferred_cap"],
            caps["rm_width"], batch=batch,
        ),
        widen_state=lambda s, caps: sp.widen(s, **caps),
        flag_axes=("dot_cap", "deferred_cap"),
        slots_fn=sp.changed_dots,
        compact_fn=sp.compact,
    )


def _plan_dense() -> _StreamPlan:
    from ..ops import orswot as ops

    return _StreamPlan(
        kind="orswot_stream_fold",
        join_fn=ops.join,
        fold_fn=ops.fold,
        caps_of=lambda s: {
            "n_elems": s.ctr.shape[-2], "n_actors": s.top.shape[-1],
            "deferred_cap": s.dvalid.shape[-1],
        },
        empty=lambda caps, batch: ops.empty(
            caps["n_elems"], caps["n_actors"], caps["deferred_cap"],
            batch=batch,
        ),
        widen_state=lambda s, caps: ops.widen(s, **caps),
        flag_axes=("deferred_cap",),
        slots_fn=ops.changed_members,
        compact_fn=ops.compact,
    )


def _plan_sparse_mvmap(sibling_cap: int) -> _StreamPlan:
    from ..ops import sparse_mvmap as smv

    return _StreamPlan(
        kind=f"sparse_mvmap_stream_fold_s{sibling_cap}",
        join_fn=partial(smv.join, sibling_cap=sibling_cap),
        fold_fn=partial(smv.fold, sibling_cap=sibling_cap),
        caps_of=lambda s: {
            "cell_cap": s.kid.shape[-1], "n_actors": s.top.shape[-1],
            "deferred_cap": s.kidx.shape[-2], "rm_width": s.kidx.shape[-1],
        },
        empty=lambda caps, batch: smv.empty(
            caps["cell_cap"], caps["n_actors"], caps["deferred_cap"],
            caps["rm_width"], batch=batch,
        ),
        widen_state=lambda s, caps: smv.widen(s, **caps),
        # The sibling lane is a STATIC join arg, not a state axis — a
        # sibling overflow cannot be widened mid-stream (re-enter with a
        # larger sibling_cap instead), hence the "" lane.
        flag_axes=("cell_cap", "deferred_cap", ""),
        slots_fn=smv.changed_cells,
        compact_fn=smv.compact,
    )


def _plan_sparse_sharded() -> _StreamPlan:
    from ..ops import sparse_orswot as sp

    base = _plan_sparse()
    return _StreamPlan(
        kind="sparse_sharded_stream_fold",
        join_fn=sp.join,
        fold_fn=sp.fold,
        caps_of=base.caps_of,
        empty=base.empty,
        widen_state=base.widen_state,
        # Widening an element-sharded stream would have to repack every
        # shard consistently; unsupported — size the shard caps up front.
        flag_axes=(),
        slots_fn=sp.changed_dots,
        compact_fn=sp.compact,
        sum_axes=(ELEMENT_AXIS,),
        sharded=True,
    )


# ---- the generic block loop -----------------------------------------------

def _specs(plan: _StreamPlan, template) -> Tuple[Any, Any]:
    """(acc_specs, block_specs) for the step's shard_map. Replicated
    kinds: acc replicated everywhere, blocks row-sharded over the
    replica axis. Dense ORSWOT: element axis shards the content planes
    (mesh.orswot_specs discipline). Sharded sparse: the leading shard
    axis rides the element axis on BOTH."""
    from ..ops.orswot import OrswotState
    from .mesh import orswot_out_specs, orswot_specs

    if plan.sharded:
        return (
            jax.tree.map(lambda _: P(ELEMENT_AXIS), template),
            jax.tree.map(lambda _: P(REPLICA_AXIS, ELEMENT_AXIS), template),
        )
    if isinstance(template, OrswotState):
        return orswot_out_specs(), orswot_specs()
    return (
        jax.tree.map(lambda _: P(), template),
        jax.tree.map(lambda _: P(REPLICA_AXIS), template),
    )


def _rows_of(tree) -> int:
    return jax.tree.leaves(tree)[0].shape[0]


def _ready(tree) -> bool:
    """Best-effort 'has this dispatch landed' probe (jax.Array.is_ready
    where available; conservatively True elsewhere) — feeds the
    overlap_hit counter only, never correctness."""
    leaf = jax.tree.leaves(tree)[0]
    fn = getattr(leaf, "is_ready", None)
    if not callable(fn):
        return True
    try:
        return bool(fn())
    except Exception:
        return True


def _widen_to(plan: _StreamPlan, state, caps: Dict[str, int]):
    """Widen ``state`` up to ``caps`` where narrower (no-op when equal;
    ``caps_of`` reads trailing shapes, so batched states report the
    same caps as unbatched ones)."""
    have = plan.caps_of(state)
    grow = {k: v for k, v in caps.items() if have.get(k, v) < v}
    return plan.widen_state(state, grow) if grow else state


def _stream_fold(
    plan: _StreamPlan,
    blocks: Iterable,
    mesh: Mesh,
    *,
    init=None,
    telemetry: bool = False,
    donate: bool = True,
    pipeline: bool = True,
    widen_policy=None,
    frontier=None,
    compact_every: int = 0,
    faults=None,
    wal=None,
    wal_every: int = 0,
    wal_base: int = 0,
):
    """The shared scaffold: template derivation, identity padding and
    cap-matching at staging, the double-buffered dispatch loop, the
    elastic retry, periodic compaction, telemetry accumulation, and the
    interrupt protocol. See the module docstring for semantics.

    ``faults=`` (a ``crdt_tpu.faults.FaultPlan``) injects seeded
    drop/corrupt faults on the BLOCK UPLOAD — the stream's wire: the
    staged block carries a checksum lane, the step corrupts it in-kernel
    per a draw keyed on ``(seed, block index)``, and a failed verify
    REJECTS the block (the accumulator keeps its pre-block value; a
    rejected block's overflow flags are masked so the elastic retry
    never widens for content that was not joined). ``delay`` has no
    meaning here — block order is host-driven — and is ignored. The
    per-block fate is read back host-side (one sync per block, the
    faults-mode price), and a :class:`StreamFaultReport` is appended as
    the LAST output so the caller can re-stream the lost blocks with
    ``init=acc``. The flag-off trace is byte-identical pre-flag.

    ``wal=`` (a ``crdt_tpu.durability.Wal``) makes the stream's
    interrupt contract DURABLE: every ``StreamInterrupted`` raise first
    persists a fsynced resume record (the accumulator — the exact join
    of blocks ``[0, k)`` — plus the resume index), and ``wal_every=k``
    additionally persists one every k blocks, so a HARD kill (no
    exception path at all — the flagship run's preemption case) still
    resumes from the last persisted block via
    ``durability.recover.load_stream_resume`` instead of restarting
    the fold. Resume records carry the ABSOLUTE block index
    (``wal_base + blocks_done``): a RESUMED run must pass the index it
    resumed from as ``wal_base=`` so a second kill still points at the
    true position in the original source, not a run-relative one. Each
    periodic persist syncs the in-flight accumulator to host — the
    durability price; size ``wal_every`` like a checkpoint cadence,
    not a telemetry one. The traced program is untouched."""
    rsize = mesh.shape[REPLICA_AXIS]
    esize = mesh.shape[ELEMENT_AXIS]
    faulted = faults is not None
    if faulted:
        from .. import faults as flt
    wal_b0 = wal.bytes_appended if wal is not None else 0
    wal_f0 = wal.fsyncs if wal is not None else 0

    def persist_resume(acc_now, done: int) -> None:
        """One fsynced resume record (module docstring) — a resume
        point that could vanish with the page cache is no resume
        point. ``done`` is run-relative; the record stores the
        ABSOLUTE source index (``wal_base + done``)."""
        if wal is None or acc_now is None:
            return
        jax.block_until_ready(jax.tree.leaves(acc_now))
        wal.append_resume(plan.kind, acc_now, wal_base + done)
        wal.sync()

    it = iter(blocks)

    def fetch():
        return next(it, None)

    try:
        first = fetch()
    except ValueError:
        raise  # caller bugs propagate as-is — _advance's contract
    except Exception as exc:
        metrics.count("stream.interrupted")
        persist_resume(init, 0)
        raise StreamInterrupted(
            exc, init, 0,
            fault_report=StreamFaultReport([], []) if faulted else None,
        ) from exc
    if first is None and init is None:
        raise ValueError("empty block stream and no init accumulator")

    # ---- template: caps + padded row geometry from the first block ----
    from ..ops.orswot import OrswotState

    dense = isinstance(first if first is not None else init, OrswotState)
    if first is not None:
        if dense:
            # Dense ORSWOT: the element universe must split over the mesh.
            from .mesh import pad_elements

            first = pad_elements(first, esize)
        caps = plan.caps_of(first)
        rows = _rows_of(first)
        template_rows = rows + ((-rows) % rsize)
    else:
        caps = plan.caps_of(init)
        template_rows = rsize
    if init is not None:
        init_caps = plan.caps_of(init)
        caps = {k: max(v, init_caps.get(k, v)) for k, v in caps.items()}
    if plan.sharded:
        s_axis = (jax.tree.leaves(first)[0].shape[1] if first is not None
                  else _rows_of(init))
        if s_axis != esize:
            raise ValueError(
                f"stream blocks carry {s_axis} element shards, mesh axis "
                f"is {esize}"
            )

    acc_template = (
        plan.empty(caps, batch=(esize,)) if plan.sharded
        else plan.empty(caps, batch=())
    )
    acc_specs, block_specs = _specs(plan, acc_template)
    acc_sharding = jax.tree.map(
        lambda s: NamedSharding(mesh, s), acc_specs
    )
    block_sharding = jax.tree.map(
        lambda s: NamedSharding(mesh, s), block_specs
    )

    def stage(raw):
        """Pad rows to the template, widen narrow caps, commit to the
        mesh. Returns the staged block; counts the repack fallback."""
        repack = False
        if dense:
            from .mesh import pad_elements

            padded = pad_elements(raw, esize)
            repack = padded is not raw
            raw = padded
        raw_caps = plan.caps_of(raw)
        if any(raw_caps.get(k, v) > v for k, v in caps.items()):
            raise ValueError(
                f"block caps {raw_caps} exceed the stream template {caps} "
                f"— widen the template (stream from the widest block "
                f"first) or re-chunk"
            )
        widened = _widen_to(plan, raw, caps)
        repack = repack or (widened is not raw)
        rows = _rows_of(widened)
        if rows > template_rows:
            raise ValueError(
                f"block has {rows} rows > stream template {template_rows} "
                f"— re-chunk the source"
            )
        if rows < template_rows:
            pad_batch = (
                (template_rows - rows, esize) if plan.sharded
                else (template_rows - rows,)
            )
            ident = plan.empty(caps, batch=pad_batch)
            widened = jax.tree.map(
                lambda x, p: jnp.concatenate([x, p.astype(x.dtype)], axis=0),
                widened, ident,
            )
            repack = True
        if repack and donate:
            metrics.count("stream.unaliasable_blocks")
        return jax.device_put(widened, block_sharding)

    n_ex = _exchange_count(rsize)

    def build():
        out_specs = [acc_specs, P()]
        if telemetry:
            out_specs.append(tele.specs())
        if faulted:
            out_specs.append(P())  # the block's fate code

        def body(acc, block, bix=None):
            if plan.sharded:
                acc_l = jax.tree.map(lambda x: x[0], acc)
                block_l = jax.tree.map(lambda x: x[:, 0], block)
            else:
                acc_l, block_l = acc, block
            if faulted:
                # The block upload is the stream's wire
                # (faults.block_wire: drop/corrupt draw keyed on the
                # block index, checksum verify over what arrived) — a
                # failed verify rejects the whole block (its join is
                # deselected below).
                block_l, code = flt.block_wire(faults, bix, block_l)
                code = lax.pmax(
                    lax.pmax(code, REPLICA_AXIS), ELEMENT_AXIS
                )
                reject = code > 0
            folded, of_local = plan.fold_fn(block_l)
            joined, of_cross = all_reduce_lattice(
                folded, REPLICA_AXIS, plan.join_fn, plan.fold_fn
            )
            new_acc, of_join = plan.join_fn(acc_l, joined)
            of = (
                lax.psum(
                    (of_local | of_join).astype(jnp.int32), REPLICA_AXIS
                ) > 0
            ) | of_cross
            of = lax.psum(of.astype(jnp.int32), ELEMENT_AXIS) > 0
            if faulted:
                # A rejected block's join never lands, and its overflow
                # flags must not drive the elastic widen retry.
                new_acc = flt.tree_select(~reject, new_acc, acc_l)
                of = of & ~reject
            out_acc = (
                jax.tree.map(lambda x: x[None], new_acc) if plan.sharded
                else new_acc
            )
            outs = [out_acc, of]
            if telemetry:
                slots_of = plan.slots_fn or tele.generic_slots_changed
                slots = slots_of(acc_l, new_acc)
                local_rows = _rows_of(block_l)
                outs.append(_tel_reduced(
                    new_acc, slots,
                    max(local_rows - 1, 0) + n_ex + 1,
                    tele.shipped_bytes(folded) * n_ex,
                    plan.sum_axes,
                ))
            if faulted:
                outs.append(code)
            return tuple(outs)

        if faulted:
            @partial(
                jax.shard_map,
                mesh=mesh,
                in_specs=(acc_specs, block_specs, P()),
                out_specs=tuple(out_specs),
                check_vma=False,
            )
            def step_fn(acc, block, bix):
                return body(acc, block, bix)
        else:
            @partial(
                jax.shard_map,
                mesh=mesh,
                in_specs=(acc_specs, block_specs),
                out_specs=tuple(out_specs),
                check_vma=False,
            )
            def step_fn(acc, block):
                return body(acc, block)

        return step_fn

    def step(acc, staged, bix):
        fn = _cached(
            plan.kind, (acc, staged), mesh, build, telemetry, faults,
            donate_argnums=(0,) if donate else (),
        )
        if faulted:
            return fn(acc, staged, jnp.uint32(bix))
        return fn(acc, staged)

    # ---- accumulator init --------------------------------------------
    if init is not None:
        acc = jax.device_put(_widen_to(plan, init, caps), acc_sharding)
        if donate:
            # Never consume the CALLER's buffers: a resumed stream may
            # retry with the same init, and device_put of an
            # already-matching array can alias it. One copy, then
            # zero-copy from there on.
            acc = jax.tree.map(jnp.copy, acc)
    else:
        acc = jax.device_put(acc_template, acc_sharding)

    tel = tele.zeros() if telemetry else None
    overflow = None
    blocks_done = 0
    staged_bytes = 0
    overlap_hits = 0
    dropped_blocks: list = []
    rejected_blocks: list = []

    def partial_report():
        """The lost-so-far snapshot an interrupt must carry (lists are
        copied: the exception's view must not mutate afterwards)."""
        if not faulted:
            return None
        return StreamFaultReport(list(dropped_blocks),
                                 list(rejected_blocks))
    frontier_arr = None
    reclaimed = (jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.float32))
    if compact_every:
        if plan.compact_fn is None:
            raise ValueError(f"{plan.kind}: no compaction kernel")
        frontier_arr = (
            jnp.zeros_like(acc_template.top[0] if plan.sharded
                           else acc_template.top)
            if frontier is None else jnp.asarray(frontier)
        )
    if widen_policy is not None and not plan.flag_axes:
        raise ValueError(
            f"{plan.kind}: mid-stream widening is not supported for this "
            f"kind (size capacities up front)"
        )

    metrics.count(f"stream.{plan.kind}_rounds")
    try:
        staged = stage(first) if first is not None else None
    except (StreamInterrupted, ValueError):
        raise
    except Exception as exc:
        metrics.count("stream.interrupted")
        jax.block_until_ready(jax.tree.leaves(acc))
        persist_resume(acc, 0)
        raise StreamInterrupted(
            exc, acc, 0, tel, fault_report=partial_report()
        ) from exc

    observe_depth(f"stream.{plan.kind}", first if first is not None else acc)
    with metrics.time(f"stream.{plan.kind}"):
        while staged is not None:
            staged_bytes += tele.shipped_bytes(staged)
            if widen_policy is None:
                out = step(acc, staged, blocks_done)
            else:
                # Elastic retry: snapshot the accumulator (the donated
                # step consumes it; the join is idempotent, so
                # re-entering from the snapshot is sound), check flags
                # per block — a host sync — widen the implicated axes
                # and re-enter: gossip_elastic's overflow→widen→resume
                # loop, one block at a time.
                attempts = 0
                while True:
                    snap = jax.tree.map(jnp.copy, acc) if donate else acc
                    out = step(acc, staged, blocks_done)
                    flags = jnp.atleast_1d(out[1])
                    if not bool(jnp.any(flags)):
                        break
                    hot = tuple(
                        axis
                        for lane, axis in enumerate(plan.flag_axes)
                        if lane < flags.shape[0] and bool(flags[lane])
                        and axis
                    )
                    if not hot:
                        raise RuntimeError(
                            f"{plan.kind}: overflow lane not recoverable "
                            f"by widening (flags={flags})"
                        )
                    if attempts >= widen_policy.max_migrations:
                        raise RuntimeError(
                            f"stream still overflowing after {attempts} "
                            f"migrations (caps: {caps}) — raise "
                            f"policy.factor or max_migrations"
                        )
                    from .. import elastic as el

                    caps.update({
                        ax: el._grown(caps[ax], widen_policy.factor)
                        for ax in hot
                    })
                    metrics.count("stream.widen_retries")
                    acc = jax.device_put(
                        _widen_to(plan, snap, caps), acc_sharding
                    )
                    staged = jax.device_put(
                        _widen_to(plan, staged, caps), block_sharding
                    )
                    attempts += 1
            if faulted:
                # One host sync per block — the faults-mode price; the
                # fate feeds the report the caller re-streams from.
                code = int(out[-1])
                if code == 1:
                    dropped_blocks.append(blocks_done)
                elif code == 2:
                    rejected_blocks.append(blocks_done)
                out = out[:-1]
            acc = out[0]
            overflow = out[1] if overflow is None else overflow | out[1]
            if telemetry:
                tel = tele.combine(tel, out[2])
            blocks_done += 1
            if compact_every and blocks_done % compact_every == 0:
                acc, reclaimed = _compact_acc(
                    plan, acc, frontier_arr, reclaimed, acc_sharding
                )
            if wal_every and blocks_done % wal_every == 0:
                persist_resume(acc, blocks_done)
            if not pipeline:
                jax.block_until_ready(jax.tree.leaves(acc))
            elif not _ready(acc):
                # The next staging is issued while this block's join is
                # still in flight: the upload DMA overlaps the kernels.
                overlap_hits += 1
            staged = _advance(
                fetch, stage, acc, tel, blocks_done, partial_report,
                persist_resume,
            )
        jax.block_until_ready(jax.tree.leaves(acc))
        persist_resume(acc, blocks_done)

    if overflow is None:
        overflow = jnp.zeros((), bool)
    metrics.count("stream.blocks", blocks_done)
    metrics.count("stream.staged_bytes", staged_bytes)
    metrics.count("stream.overlap_hit", overlap_hits)
    metrics.observe("stream.acc_bytes", state_nbytes(acc))
    if compact_every:
        from ..reclaim import record_reclaim

        record_reclaim(
            f"stream.{plan.kind}", int(reclaimed[0]), float(reclaimed[1])
        )
    report = None
    if faulted:
        report = StreamFaultReport(dropped_blocks, rejected_blocks)
        if dropped_blocks:
            metrics.count("faults.packets_dropped", len(dropped_blocks))
        if rejected_blocks:
            metrics.count("faults.packets_rejected", len(rejected_blocks))
        if dropped_blocks or rejected_blocks:
            # A non-empty fault report is a postmortem boundary: the
            # caller must re-stream these blocks — record which, and
            # write the flight artifact (obs/recorder.py no-ops both
            # when no recorder is installed).
            from .. import obs

            obs.emit(
                "stream_fault_report",
                dropped=list(dropped_blocks),
                rejected=list(rejected_blocks),
            )
            obs.auto_dump(
                "stream_fault_report",
                dropped=len(dropped_blocks), rejected=len(rejected_blocks),
            )
    if telemetry:
        tel = tel._replace(
            stream_blocks=jnp.uint32(blocks_done),
            stream_staged_bytes=jnp.float32(staged_bytes),
            stream_overlap_hit=jnp.uint32(overlap_hits),
            reclaimed_slots=tel.reclaimed_slots + reclaimed[0],
            reclaimed_bytes=tel.reclaimed_bytes + reclaimed[1],
        )
        if wal is not None:
            tel = tel._replace(
                wal_bytes=jnp.float32(wal.bytes_appended - wal_b0),
                wal_fsyncs=jnp.uint32(wal.fsyncs - wal_f0),
            )
        if faulted:
            tel = tel._replace(
                faults_dropped=jnp.uint32(len(dropped_blocks)),
                faults_rejected=jnp.uint32(len(rejected_blocks)),
            )
        if tele.is_concrete(tel):
            tele.record(plan.kind, tel)
        if faulted:
            return acc, overflow, tel, report
        return acc, overflow, tel
    if faulted:
        return acc, overflow, report
    return acc, overflow


def _advance(fetch, stage, acc, tel, blocks_done, partial_report,
             persist_resume=lambda acc, done: None):
    """Fetch + stage the next block; a failure interrupts the stream
    with the accumulator intact (the failed block never entered a
    step) and, on a faulted run, the lost-so-far report
    (``partial_report`` is the driver's snapshot closure) — persisting
    the resume point first when the run is ``wal=``-durable. Contract
    violations (ValueError from ``stage``) propagate as-is — they are
    caller bugs, not stream faults."""
    try:
        nxt = fetch()
        return stage(nxt) if nxt is not None else None
    except ValueError:
        raise
    except Exception as exc:
        metrics.count("stream.interrupted")
        jax.block_until_ready(jax.tree.leaves(acc))
        persist_resume(acc, blocks_done)
        raise StreamInterrupted(
            exc, acc, blocks_done, tel, fault_report=partial_report()
        ) from exc


def _compact_acc(plan, acc, frontier_arr, reclaimed, acc_sharding):
    """One causal-stability compaction of the accumulator (reclaim/):
    async dispatch, no host sync; freed counts accumulate on device."""
    acc2, freed, freed_b = plan.compact_fn(acc, frontier_arr)
    acc2 = jax.device_put(acc2, acc_sharding)
    return acc2, (
        reclaimed[0] + jnp.sum(freed, dtype=jnp.uint32),
        reclaimed[1] + jnp.sum(freed_b, dtype=jnp.float32).astype(jnp.float32),
    )


# ---- public entry points --------------------------------------------------

def mesh_stream_fold_sparse(
    blocks: Iterable, mesh: Mesh, *, init=None, telemetry: bool = False,
    donate: bool = True, pipeline: bool = True, widen_policy=None,
    frontier=None, compact_every: int = 0, faults=None, wal=None,
    wal_every: int = 0, wal_base: int = 0,
):
    """Stream-fold SPARSE (segment-encoded) ORSWOT replica blocks
    ``[B, ...]`` into one converged state — the flagship arbitrary-N
    driver (``bench.py --flagship`` runs the 10,240 x 1M shape through
    it). Returns ``(state, overflow[2[, Telemetry]])``; semantics and
    flags (incl. the ``wal=`` durable-resume contract) per the module
    docstring."""
    return _stream_fold(
        _plan_sparse(), blocks, mesh, init=init, telemetry=telemetry,
        donate=donate, pipeline=pipeline, widen_policy=widen_policy,
        frontier=frontier, compact_every=compact_every, faults=faults,
        wal=wal, wal_every=wal_every, wal_base=wal_base,
    )


def mesh_stream_fold(
    blocks: Iterable, mesh: Mesh, *, init=None, telemetry: bool = False,
    donate: bool = True, pipeline: bool = True, widen_policy=None,
    frontier=None, compact_every: int = 0, faults=None, wal=None,
    wal_every: int = 0, wal_base: int = 0,
):
    """Stream-fold DENSE ORSWOT replica blocks ``[B, E, A]`` (content
    planes element-sharded over the mesh, ``mesh.orswot_specs``
    discipline). Returns ``(state, overflow[, Telemetry]])``."""
    return _stream_fold(
        _plan_dense(), blocks, mesh, init=init, telemetry=telemetry,
        donate=donate, pipeline=pipeline, widen_policy=widen_policy,
        frontier=frontier, compact_every=compact_every, faults=faults,
        wal=wal, wal_every=wal_every, wal_base=wal_base,
    )


def mesh_stream_fold_sparse_mvmap(
    blocks: Iterable, mesh: Mesh, *, sibling_cap: int = 4, init=None,
    telemetry: bool = False, donate: bool = True, pipeline: bool = True,
    widen_policy=None, frontier=None, compact_every: int = 0, faults=None,
    wal=None, wal_every: int = 0, wal_base: int = 0,
):
    """Stream-fold SPARSE ``Map<K, MVReg>`` replica blocks
    (ops/sparse_mvmap) — the register-family arbitrary-N driver.
    Returns ``(state, overflow[3][, Telemetry]])``. A sibling-cap
    overflow is NOT recoverable mid-stream (static join arg); re-enter
    with a larger ``sibling_cap``."""
    return _stream_fold(
        _plan_sparse_mvmap(sibling_cap), blocks, mesh, init=init,
        telemetry=telemetry, donate=donate, pipeline=pipeline,
        widen_policy=widen_policy, frontier=frontier,
        compact_every=compact_every, faults=faults, wal=wal,
        wal_every=wal_every, wal_base=wal_base,
    )


def mesh_stream_fold_sparse_sharded(
    blocks: Iterable, mesh: Mesh, *, init=None, telemetry: bool = False,
    donate: bool = True, pipeline: bool = True, frontier=None,
    compact_every: int = 0, faults=None, wal=None, wal_every: int = 0,
    wal_base: int = 0,
):
    """Stream-fold element-SHARDED sparse replica blocks ``[B, S, ...]``
    (from ``sparse_shard.split_segments``; S must equal the mesh's
    element axis): shard-local joins are exact (restriction commutes
    with join), so streaming composes with element sharding at no extra
    collective. The accumulator keeps the ``[S, ...]`` element-sharded
    layout. Mid-stream widening is unsupported here (size shard caps up
    front). Returns ``(state [S, ...], overflow[, Telemetry]])``."""
    return _stream_fold(
        _plan_sparse_sharded(), blocks, mesh, init=init,
        telemetry=telemetry, donate=donate, pipeline=pipeline,
        frontier=frontier, compact_every=compact_every, faults=faults,
        wal=wal, wal_every=wal_every, wal_base=wal_base,
    )


def iter_blocks(states, block_rows: int):
    """Slice a co-resident ``[N, ...]`` batch into ``[block_rows, ...]``
    stream blocks — the convenience bridge for populations that DO fit
    (tests, subsampled bit-identity gates) and the reference shape for
    real sources (host shards, checkpoint readers, DCN receivers)."""
    n = _rows_of(states)
    for lo in range(0, n, block_rows):
        yield jax.tree.map(lambda x: x[lo: lo + block_rows], states)


# ---- static-analysis registration (crdt_tpu.analysis) --------------------
#
# Every stream entry point registers kind + example-args builder +
# donation arity, so the aliasing gate (tools/check_aliasing.py) pins
# the accumulator's input_output_alias and the jit-lint walks the step
# program — the same coverage contract as the gossip/fold family. The
# registered args ARE the cached step's args: (accumulator, block).

def _register():
    from ..analysis import gate_states as gs
    from ..analysis.registry import register_entry_point

    def reg(name, kind, mk_acc, mk_block, invoke):
        register_entry_point(
            name, kind=kind,
            make_args=lambda mesh: (mk_acc(mesh), mk_block(mesh)),
            invoke=invoke, n_donated=1,
        )

    def sparse_acc(mesh):
        from ..ops import sparse_orswot as sp

        return sp.empty(gs.GE, gs.GA, gs.GD, 8)

    def dense_acc(mesh):
        from ..ops import orswot as ops

        return ops.empty(gs.GE, gs.GA, gs.GD)

    def mvmap_acc(mesh):
        from ..ops import sparse_mvmap as smv

        return smv.empty(gs.GE, gs.GA, gs.GD, 8)

    def sharded_acc(mesh):
        from ..ops import sparse_orswot as sp

        return sp.empty(
            gs.GE, gs.GA, gs.GD, 8, batch=(mesh.shape[ELEMENT_AXIS],)
        )

    reg(
        "mesh_stream_fold_sparse", "sparse_stream_fold",
        sparse_acc, lambda mesh: gs.mk_sparse(gs.replicas(mesh)),
        lambda mesh, args: mesh_stream_fold_sparse(
            [args[1]], mesh, init=args[0], donate=True
        ),
    )
    reg(
        "mesh_stream_fold", "orswot_stream_fold",
        dense_acc, lambda mesh: gs.mk_dense(gs.replicas(mesh)),
        lambda mesh, args: mesh_stream_fold(
            [args[1]], mesh, init=args[0], donate=True
        ),
    )
    reg(
        "mesh_stream_fold_sparse_mvmap", "sparse_mvmap_stream_fold_s4",
        mvmap_acc, lambda mesh: gs.mk_sparse_mvmap(gs.replicas(mesh)),
        lambda mesh, args: mesh_stream_fold_sparse_mvmap(
            [args[1]], mesh, init=args[0], donate=True
        ),
    )
    def sharded_block(mesh):
        from .sparse_shard import split_segments

        return split_segments(
            gs.mk_sparse(gs.replicas(mesh)), mesh.shape[ELEMENT_AXIS]
        )

    reg(
        "mesh_stream_fold_sparse_sharded", "sparse_sharded_stream_fold",
        sharded_acc, sharded_block,
        lambda mesh, args: mesh_stream_fold_sparse_sharded(
            [args[1]], mesh, init=args[0], donate=True
        ),
    )

    from ..analysis.registry import register_fault_surface

    for name in (
        "mesh_stream_fold", "mesh_stream_fold_sparse",
        "mesh_stream_fold_sparse_mvmap", "mesh_stream_fold_sparse_sharded",
    ):
        register_fault_surface(name, module=__name__)

    from ..analysis.registry import register_obs_event

    register_obs_event(
        "stream_fault_report", subsystem="parallel.stream",
        fields=("dropped", "rejected"), module=__name__,
    )


_register()
