"""Multi-host / multi-process entry points (SURVEY.md §6.8).

The reference ships serde bytes and leaves transport to the caller; the
TPU build's NCCL-equivalent is XLA collectives over ICI within a slice
and DCN across slices. This module wires the multi-process runtime:

- ``initialize`` — ``jax.distributed.initialize`` (coordinator
  rendezvous; must run before the backend initialises),
- ``global_mesh`` — a ``(replica, element)`` mesh over ALL processes'
  devices with the replica axis spanning processes. Element shards
  never communicate (the join is element-parallel, mesh.py), so the
  only cross-process traffic is the replica-axis lattice-join
  all-reduce — one state per round over DCN, exactly what the mesh.py
  docstring prescribes for DCN-facing axes,
- ``host_to_global`` — lift per-process host-local replica rows into a
  global sharded array so ``mesh_fold`` / ``mesh_gossip`` run unchanged
  on the multi-host mesh (the same anti-entropy program, now SPMD over
  processes).

Tested by tests/test_multihost.py with two local CPU processes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .mesh import ELEMENT_AXIS, REPLICA_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join (or start) the distributed runtime. Call before any JAX
    backend touch; arguments default to JAX's env-var autodetection
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID or
    the cloud-TPU metadata server)."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(n_element_shards: int = 1):
    """A ``(replica, element)`` mesh over every process's devices.

    ``jax.devices()`` orders devices process-major, so a row-major
    reshape puts element shards on neighbouring (same-process, ICI)
    devices and lets the replica axis span processes — replica-join
    traffic is the only thing that crosses DCN."""
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices())
    n = len(devices)
    # Element shards must fit INSIDE a process: the layout promise is
    # that element traffic never crosses DCN, which the total-count
    # check alone would silently break (shards straddling processes).
    local = jax.local_device_count()
    if local % n_element_shards:
        raise ValueError(
            f"{n_element_shards} element shards do not divide the "
            f"{local} per-process devices — element shards would "
            f"straddle processes (DCN)"
        )
    grid = devices.reshape(n // n_element_shards, n_element_shards)
    return Mesh(grid, (REPLICA_AXIS, ELEMENT_AXIS))


def host_to_global(local_state, mesh, specs):
    """Lift host-local arrays (this process's replica rows, full element
    extent) into global sharded arrays laid out per ``specs`` — the
    hand-off between per-host state ingestion and the mesh-wide
    anti-entropy program."""
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        local_state, mesh, specs
    )


def global_to_host(global_state):
    """Host copy of a fully-replicated global result (the converged
    state every process receives after ``mesh_fold``)."""
    import jax

    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), global_state)
