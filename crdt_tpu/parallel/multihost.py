"""Multi-host / multi-process entry points (SURVEY.md §6.8).

The reference ships serde bytes and leaves transport to the caller; the
TPU build's NCCL-equivalent is XLA collectives over ICI within a slice
and DCN across slices. This module wires the multi-process runtime:

- ``initialize`` — ``jax.distributed.initialize`` (coordinator
  rendezvous; must run before the backend initialises),
- ``global_mesh`` — a ``(replica, element)`` mesh over ALL processes'
  devices with the replica axis spanning processes. Element shards
  never communicate (the join is element-parallel, mesh.py), so the
  only cross-process traffic is the replica-axis lattice-join
  all-reduce — one state per round over DCN, exactly what the mesh.py
  docstring prescribes for DCN-facing axes,
- ``host_to_global`` — lift per-process host-local replica rows into a
  global sharded array so ``mesh_fold`` / ``mesh_gossip`` run unchanged
  on the multi-host mesh (the same anti-entropy program, now SPMD over
  processes).

Tested by tests/test_multihost.py with two local CPU processes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .mesh import ELEMENT_AXIS, REPLICA_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join (or start) the distributed runtime. Call before any JAX
    backend touch; arguments default to JAX's env-var autodetection
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID or
    the cloud-TPU metadata server)."""
    import jax

    if "cpu" in str(jax.config.jax_platforms or "cpu").lower():
        # The default XLA CPU client rejects multiprocess programs
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"); the gloo transport is the CPU stand-in for
        # ICI/DCN. Harmless on TPU (the flag only shapes CPU-client
        # construction, which happens after this call).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jax without the option: keep its default
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(n_element_shards: int = 1):
    """A ``(replica, element)`` mesh over every process's devices.

    ``jax.devices()`` orders devices process-major, so a row-major
    reshape puts element shards on neighbouring (same-process, ICI)
    devices and lets the replica axis span processes — replica-join
    traffic is the only thing that crosses DCN."""
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices())
    n = len(devices)
    # Element shards must fit INSIDE a process: the layout promise is
    # that element traffic never crosses DCN, which the total-count
    # check alone would silently break (shards straddling processes).
    local = jax.local_device_count()
    if local % n_element_shards:
        raise ValueError(
            f"{n_element_shards} element shards do not divide the "
            f"{local} per-process devices — element shards would "
            f"straddle processes (DCN)"
        )
    grid = devices.reshape(n // n_element_shards, n_element_shards)
    return Mesh(grid, (REPLICA_AXIS, ELEMENT_AXIS))


def host_to_global(local_state, mesh, specs):
    """Lift host-local arrays (this process's replica rows, full element
    extent) into global sharded arrays laid out per ``specs`` — the
    hand-off between per-host state ingestion and the mesh-wide
    anti-entropy program."""
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        local_state, mesh, specs
    )


def global_to_host(global_state):
    """Host copy of a fully-replicated global result (the converged
    state every process receives after ``mesh_fold``)."""
    import jax

    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), global_state)


def _refuse_timeout(retry, op: str) -> None:
    """Collective exchanges cannot be safely timed out per attempt: the
    abandoned worker thread may still issue its collectives and mispair
    with the retry's on peer processes (faults/retry.py module
    caveats). Fail loudly instead of corrupting rounds cluster-wide."""
    if retry is not None and retry.timeout is not None:
        raise ValueError(
            f"{op}: RetryPolicy.timeout is not supported around "
            f"collective exchanges — an abandoned timed-out attempt "
            f"can mispair its in-flight collectives with the retry's; "
            f"use timeout=None here"
        )


def _allgather_host(arr: np.ndarray, retry=None):
    """All-gather a per-process host array of possibly different lengths
    (axis 0); returns the per-process list. Lengths are exchanged first,
    data rides one padded device all-gather.

    ``retry=`` (a ``crdt_tpu.faults.RetryPolicy``) wraps the exchange in
    exponential-backoff-with-jitter retries — sound because an
    all-gather of immutable host arrays is idempotent. Exhaustion raises
    ``faults.DcnExchangeFailed`` carrying ``arr`` as the last-good state
    (re-gather it later). Retries must be SYMMETRIC across processes
    (same policy everywhere) or the survivors deadlock, and a
    per-attempt ``timeout`` is REFUSED: a timed-out attempt's abandoned
    thread could still issue its collectives and mispair with the
    retry's fresh ones on peer processes (faults/retry.py documents
    both caveats)."""
    _refuse_timeout(retry, "_allgather_host")

    def once():
        import jax  # noqa: F401  (backend must be up for the gather)
        from jax.experimental import multihost_utils

        n = np.asarray([arr.shape[0]], np.int64)
        lens = multihost_utils.process_allgather(n).reshape(-1)
        maxlen = int(lens.max())
        padded = np.zeros((maxlen, *arr.shape[1:]), arr.dtype)
        padded[: arr.shape[0]] = arr
        gathered = np.asarray(multihost_utils.process_allgather(padded))
        if gathered.ndim == padded.ndim:
            # Single-process process_allgather returns the input WITHOUT
            # the leading process axis (jax shape quirk) — normalize so
            # the degenerate serve-tier self-gather slices correctly.
            gathered = gathered[None]
        return [gathered[p, : int(lens[p])] for p in range(len(lens))]

    if retry is None:
        return once()
    from ..faults.retry import with_retries

    return with_retries(
        once, retry, op="allgather_host", last_good=arr
    )


def sync_tenant_rows(wire: dict, retry=None):
    """All-gather per-host serving-tier wire dicts (uniform string
    field names, numpy array values — the tenant-shard anti-entropy
    exchange of crdt_tpu/serve/shard.py: each host exports packed
    tenant rows, every host receives every export and joins the rows
    it OWNS). Returns the per-process list of wire dicts, this
    process's own included.

    ``retry=`` hardens the DCN gathers exactly like :func:`sync_list`
    (idempotent gathers of immutable exports; symmetric-policy and
    no-per-attempt-timeout caveats apply) — and because this is a
    MULTI-collective exchange (one gather pair per field), each retried
    attempt opens with the same attempt-number lockstep check, so a
    one-sided transient failure surfaces as ``DcnExchangeFailed``
    instead of mispairing field bytes."""
    import jax

    _refuse_timeout(retry, "sync_tenant_rows")
    fields = sorted(wire)

    def gather_all():
        return {f: _allgather_host(np.asarray(wire[f])) for f in fields}

    if retry is None:
        gathered = gather_all()
    else:
        from ..faults.retry import DcnExchangeFailed, with_retries

        attempt_box = {"n": 0}

        def gather_all_guarded():
            tag = _allgather_host(
                np.asarray([attempt_box["n"]], np.int32)
            )
            attempt_box["n"] += 1
            if len({int(t[0]) for t in tag}) != 1:
                raise DcnExchangeFailed(
                    "sync_tenant_rows", attempt_box["n"],
                    RuntimeError(
                        "attempt-number mismatch across processes — a "
                        "one-sided retry desynced the collective "
                        "sequence; re-enter sync_tenant_rows on every "
                        "process"
                    ),
                    last_good=wire,
                )
            return gather_all()

        gathered = with_retries(
            gather_all_guarded, retry, op="sync_tenant_rows",
            last_good=wire,
        )
    return [
        {f: gathered[f][p] for f in fields}
        for p in range(jax.process_count())
    ]


def sync_list(model, since: int = 0, retry=None) -> int:
    """Converge ``BatchedList`` identifier universes across processes
    (SURVEY.md §4.5 — the reference ships ``Op::Insert { id, val }``
    bytes to any replica; here the op log's identifier paths ride a DCN
    all-gather). Each process exports its local ops ``[since, ...)``,
    gathers every process's export, and ingests the remote ones in
    process order — identifier paths are globally unique and totally
    ordered by construction, so every process reconverges to the SAME
    total order regardless of mint site. Returns the new local-op
    watermark to pass as ``since`` next round.

    Device state re-permutes with the growing universe; run
    ``model.apply_trace_to_all()`` afterwards to land the new ops.

    ``retry=`` (a ``crdt_tpu.faults.RetryPolicy``) hardens the DCN
    gather — the only cross-process exchange here — with
    exponential-backoff-with-jitter retries (gathers of an immutable
    export are idempotent; local ingestion below never retries, so a
    flaky DCN cannot double-apply). Exhaustion raises
    ``faults.DcnExchangeFailed`` carrying ``since`` as the last-good
    watermark: ops below it are already everywhere — re-sync later from
    it, nothing is lost. Same symmetric-retry and no-per-attempt-timeout
    caveats as ``_allgather_host`` — and because this exchange is SEVEN
    collectives, each retried attempt opens with an attempt-number
    lockstep check, so a one-sided failure (this process erroring while
    peers sailed on) surfaces as ``DcnExchangeFailed`` instead of
    silently ingesting mispaired field bytes."""
    import jax

    _refuse_timeout(retry, "sync_list")
    wire = dict(model.export_ops(since))
    # The gather rides device arrays; without x64 mode jax silently
    # truncates 64-bit dtypes to 32 (config.py documents the hazard), so
    # wide fields ship as checked/split 32-bit lanes and reassemble on
    # the host. cctr (engine mint counters, uint64) splits hi/lo; cidx
    # and counts are range-checked into int32.
    for f in ("cidx", "counts"):
        if wire[f].size and wire[f].max() > np.iinfo(np.int32).max:
            raise OverflowError(f"wire field {f} exceeds int32 range")
        wire[f] = wire[f].astype(np.int32)
    cctr = wire.pop("cctr")
    wire["cctr_hi"] = (cctr >> np.uint64(32)).astype(np.uint32)
    wire["cctr_lo"] = cctr.astype(np.uint32)
    fields = ("kinds", "values", "counts", "cidx", "cactor",
              "cctr_hi", "cctr_lo")

    def gather_all():
        return {f: _allgather_host(np.asarray(wire[f])) for f in fields}

    if retry is None:
        gathered = gather_all()
    else:
        from ..faults.retry import DcnExchangeFailed, with_retries

        attempt_box = {"n": 0}

        def gather_all_guarded():
            # One-sided-failure guard: retrying this SEVEN-collective
            # exchange is only safe when every process re-enters it
            # together — a local exception while peers sailed on would
            # pair our restarted field gathers with their LATER ones
            # and silently ingest mispaired bytes. Each attempt opens
            # with a tiny attempt-number all-gather: lockstep peers
            # agree (one cheap round-trip); a desynced peer either
            # disagrees (caught here, non-retryable) or is mid-field,
            # where the tag's shape cannot pair cleanly (loud backend
            # error). Either way corruption becomes failure.
            tag = _allgather_host(
                np.asarray([attempt_box["n"]], np.int32)
            )
            attempt_box["n"] += 1
            if len({int(t[0]) for t in tag}) != 1:
                raise DcnExchangeFailed(
                    "sync_list", attempt_box["n"],
                    RuntimeError(
                        "attempt-number mismatch across processes — a "
                        "one-sided retry desynced the collective "
                        "sequence; re-enter sync_list on every process"
                    ),
                    last_good=since,
                )
            return gather_all()

        gathered = with_retries(
            gather_all_guarded, retry, op="sync_list", last_good=since
        )
    me = jax.process_index()
    for p in range(jax.process_count()):
        if p == me:
            continue
        remote = {f: gathered[f][p] for f in fields}
        remote["cctr"] = (
            remote.pop("cctr_hi").astype(np.uint64) << np.uint64(32)
        ) | remote.pop("cctr_lo").astype(np.uint64)
        remote["counts"] = remote["counts"].astype(np.int64)
        remote["cidx"] = remote["cidx"].astype(np.int64)
        model.ingest_remote_ops(remote)
    # Ops below this watermark are now known to every process (each
    # ingested everyone's export this round) — the next sync ships only
    # ops minted after it.
    return len(model.op_handles)
