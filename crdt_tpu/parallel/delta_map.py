"""δ-state anti-entropy for the composition layer: ``Map<K, MVReg>``.

Same discipline as :mod:`.delta` (which documents the theory and the
two failure modes that force per-row contexts and domain forwarding),
applied to the config-4 map slabs: a delta packet ships up to ``cap``
(key index, content slots, per-key causal context) triples plus the
bounded parked keyset-remove buffer. Per-key survival is the full
``ops.map.join`` rule restricted to the packet keys — content survives
iff the peer holds the same witness dot or the dot is unseen by the
peer's per-key context — so convergence is inherited from the join, not
re-proven.

A key's forwarding context covers the dots the replica can attest for
THAT KEY: the witness dots it saw there (live or since superseded) plus
any keyset-rm clocks applied there — and nothing cross-key (see
``_key_knowledge`` for why a put's stored clock must stay out). Track
with ``interval_accumulate_map`` or from op logs at op granularity, as
in delta.py's contract.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ..ops import map as map_ops
from ..ops.map import (
    MapState,
    _apply_parked,
    _canon_child,
    _dot_in,
    _drop_stale_deferred,
)
from ..ops.mvreg import MVRegState
from ..ops.orswot import _compact_deferred, _dedupe_deferred
from .mesh import (
    ELEMENT_AXIS,
    REPLICA_AXIS,
    map_specs,
    pad_keys,
    pad_replicas_map,
)


class MapDeltaPacket(NamedTuple):
    """One replica's bounded map delta (shard-local key indices)."""

    idx: jax.Array     # [C] int32
    child: MVRegState  # [C, S(, A)] content slots of the shipped keys
    ctxs: jax.Array    # [C, A] per-key causal context
    valid: jax.Array   # [C] bool
    dcl: jax.Array     # [D, A] parked keyset-removes ride whole
    dkeys: jax.Array   # [D, K]
    dvalid: jax.Array  # [D]


def _key_knowledge(child: MVRegState) -> jax.Array:
    """Per-key clock of the WITNESS DOTS the content slots attest.
    child [..., K, S] → [..., K, A].

    Deliberately excludes the slots' write clocks: a put's stored clock
    is its minter's whole-map top at mint time — CROSS-key knowledge.
    Folding it into a per-key context lets a delta claim dots of other
    keys that its slots cannot account for, which kills concurrent
    siblings the full join keeps (found the hard way; the A/B gates in
    test_delta_map.py pin it). Superseded-sibling removal knowledge
    still propagates: whoever held the sibling witnessed its dot, so
    the dot enters that replica's tracking at this key."""
    a = child.clk.shape[-1]
    wdot = (
        jax.nn.one_hot(child.wact, a, dtype=child.wctr.dtype)
        * child.wctr[..., None]
    )
    return jnp.max(jnp.where(child.valid[..., None], wdot, 0), axis=-2)


def interval_accumulate_map(
    dirty: jax.Array, fctx: jax.Array, old: MapState, new: MapState
) -> Tuple[jax.Array, jax.Array]:
    """Fold one mutation step into (dirty, fctx): changed keys become
    dirty and their context absorbs both endpoints' per-key knowledge."""
    changed = jnp.any(
        jnp.stack(
            [
                jnp.any(old.child.wact != new.child.wact, axis=-1),
                jnp.any(old.child.wctr != new.child.wctr, axis=-1),
                jnp.any(old.child.valid != new.child.valid, axis=-1),
                jnp.any(old.child.clk != new.child.clk, axis=(-2, -1)),
                jnp.any(old.child.val != new.child.val, axis=-1),
            ]
        ),
        axis=0,
    )
    grown = jnp.maximum(
        fctx, jnp.maximum(_key_knowledge(old.child), _key_knowledge(new.child))
    )
    return dirty | changed, jnp.where(changed[..., None], grown, fctx)


def extract_delta_map(
    state: MapState, dirty: jax.Array, fctx: jax.Array, cap: int, start=0
) -> Tuple[MapDeltaPacket, jax.Array, jax.Array]:
    """Pack up to ``cap`` dirty keys with their contexts and clear them
    locally; rotation as in delta.extract_delta. Returns
    ``(packet, dirty, fctx)``."""
    k = dirty.shape[-1]
    pos = (jnp.arange(k) - start) % k
    order = jnp.argsort(jnp.where(dirty, pos, k + pos))
    idx = order[:cap].astype(jnp.int32)
    valid = jnp.take(dirty, idx)
    rows = jax.tree.map(lambda x: jnp.take(x, idx, axis=0), state.child)
    ctxs = jnp.maximum(jnp.take(fctx, idx, axis=0), _key_knowledge(rows))
    zero = lambda x: jnp.where(
        valid.reshape((-1,) + (1,) * (x.ndim - 1)), x, jnp.zeros_like(x)
    )
    pkt = MapDeltaPacket(
        idx=idx,
        child=jax.tree.map(zero, rows),
        ctxs=jnp.where(valid[:, None], ctxs, 0),
        valid=valid,
        dcl=state.dcl,
        dkeys=state.dkeys,
        dvalid=state.dvalid,
    )
    # fctx is never cleared — monotone knowledge cache (see
    # delta.extract_delta).
    return pkt, dirty.at[idx].set(False), fctx


def _cov(clock: jax.Array, act: jax.Array, ctr: jax.Array) -> jax.Array:
    """ctr <= clock[act] per slot: [C, A] clock vs [C, S] (act, ctr)."""
    return ctr <= jnp.take_along_axis(clock, act, axis=-1)


def _replay_on_rows(rows: MVRegState, idx, dcl, dkeys, dvalid) -> MVRegState:
    """Kill covered content among packet-key rows [C, S*] under every
    parked (clock, keyset) slot, keysets gathered at ``idx`` — the
    per-row form of ops.map._apply_parked."""

    def step(valid, slot):
        cl, keys, dv = slot  # [A], [K], []
        kmask = jnp.take(keys, idx)  # [C]
        c = idx.shape[0]
        dead = (
            kmask[:, None]
            & _cov(jnp.broadcast_to(cl[None, :], (c, cl.shape[-1])),
                   rows.wact, rows.wctr)
            & dv
        )
        return valid & ~dead, None

    valid, _ = lax.scan(step, rows.valid, (dcl, dkeys, dvalid))
    return rows._replace(valid=valid)


def apply_delta_map(
    state: MapState, pkt: MapDeltaPacket, dirty: jax.Array, fctx: jax.Array
) -> Tuple[MapState, jax.Array, jax.Array, jax.Array]:
    """Join a map delta into ``state``: the ops.map.join content rule
    restricted to the packet keys, with per-key packet contexts standing
    in for the sender's top. Returns ``(state, dirty, fctx,
    overflow[2])`` — [sibling-slab, deferred] as in ops.map.join."""
    recv = jax.tree.map(lambda x: jnp.take(x, pkt.idx, axis=0), state.child)
    # Per-key receiver knowledge: honest top ∨ what packets taught about
    # THIS key. The global top must not grow mid-ring (see
    # delta.apply_delta — prefix coverage would leak cross-key claims).
    rctx = jnp.maximum(state.top[None, :], jnp.take(fctx, pkt.idx, axis=0))

    keep_r = recv.valid & (
        _dot_in(recv, pkt.child) | ~_cov(pkt.ctxs, recv.wact, recv.wctr)
    )
    keep_p = pkt.child.valid & (
        _dot_in(pkt.child, recv) | ~_cov(rctx, pkt.child.wact, pkt.child.wctr)
    )
    union = MVRegState(
        wact=jnp.concatenate([recv.wact, pkt.child.wact], axis=-1),
        wctr=jnp.concatenate([recv.wctr, pkt.child.wctr], axis=-1),
        clk=jnp.concatenate([recv.clk, pkt.child.clk], axis=-2),
        val=jnp.concatenate([recv.val, pkt.child.val], axis=-1),
        valid=jnp.concatenate([keep_r, keep_p], axis=-1),
    )
    s2 = union.wact.shape[-1]
    dup = (
        (union.wact[..., :, None] == union.wact[..., None, :])
        & (union.wctr[..., :, None] == union.wctr[..., None, :])
        & union.valid[..., :, None]
        & union.valid[..., None, :]
    )
    first = jnp.argmax(dup, axis=-1)
    union = union._replace(valid=union.valid & (first == jnp.arange(s2)))

    # Union the deferred keyset buffers FIRST and replay them on the
    # double-width union before the capacity check — as ops.map.join
    # does ("a union that only transiently exceeds capacity does not
    # flag overflow"): a parked remove arriving in this very packet may
    # be what keeps the survivors within the slab.
    dcl = jnp.concatenate([state.dcl, pkt.dcl], axis=-2)
    dkeys = jnp.concatenate([state.dkeys, pkt.dkeys], axis=-2)
    dvalid = jnp.concatenate([state.dvalid, pkt.dvalid], axis=-1)
    dcl, dkeys, dvalid = _dedupe_deferred(dcl, dkeys, dvalid)
    union = _replay_on_rows(union, pkt.idx, dcl, dkeys, dvalid)

    union = _canon_child(union)
    scap = state.child.wact.shape[-1]
    slab_of = jnp.any(
        (jnp.sum(union.valid, axis=-1) > scap) & pkt.valid
    )
    merged = jax.tree.map(
        lambda x: x[..., :scap, :] if x.ndim == union.clk.ndim else x[..., :scap],
        union,
    )
    # Skip invalid packet slots; scatter merged rows back.
    put = lambda whole, rows, per_row: whole.at[pkt.idx].set(
        jnp.where(
            pkt.valid.reshape((-1,) + (1,) * (rows.ndim - 1)), rows, per_row
        )
    )
    child = jax.tree.map(
        lambda whole, rows, old: put(whole, rows, old),
        state.child,
        merged,
        recv,
    )
    top = state.top  # never grows mid-ring; the closure restores it

    st = MapState(top=top, child=child, dcl=dcl, dkeys=dkeys, dvalid=dvalid)
    before = st.child
    st = _drop_stale_deferred(_apply_parked(st))
    dcl, dkeys, dvalid, d_of = _compact_deferred(
        st.dcl, st.dkeys, st.dvalid, state.dcl.shape[-2]
    )
    st = st._replace(
        child=_canon_child(st.child), dcl=dcl, dkeys=dkeys, dvalid=dvalid
    )

    # Domain forwarding + context accumulation (see delta.py).
    old_f = jnp.take(fctx, pkt.idx, axis=0)
    row_know = _key_knowledge(
        jax.tree.map(lambda x: jnp.take(x, pkt.idx, axis=0), st.child)
    )
    new_f = jnp.where(
        pkt.valid[:, None],
        jnp.maximum(jnp.maximum(old_f, pkt.ctxs), row_know),
        old_f,
    )
    fctx = fctx.at[pkt.idx].set(new_f)
    dirty = dirty.at[pkt.idx].set(jnp.take(dirty, pkt.idx) | pkt.valid)
    # A parked-remove replay that killed content is removal knowledge
    # the killed keys must forward (the delta.py analog of growing fctx
    # by the pre-replay rows): absorb the pre-replay witness dots.
    replay_changed = jnp.any(st.child.valid != before.valid, axis=-1)
    dirty = dirty | replay_changed
    fctx = jnp.maximum(
        fctx,
        jnp.where(replay_changed[:, None], _key_knowledge(before), 0),
    )
    return st, dirty, fctx, jnp.stack([slab_of, jnp.any(d_of)])


def gate_delta_map(pkt: MapDeltaPacket, digest: jax.Array) -> MapDeltaPacket:
    """Digest gate for map deltas (delta.gate_delta documents the
    two-part soundness argument): a slot is redundant only when its
    context carries NO knowledge beyond its live content's witness
    dots (``ctxs == _key_knowledge(child)`` — anything above is a
    superseded-sibling or keyset-remove the receiver may lack, and a
    top digest cannot prove otherwise) AND the receiver's frozen top
    covers those witness dots — witness dots are per-write events, so
    an honest top covering one means the receiver's store accounts for
    that exact write at this key (live or superseded) and the
    restricted join is a content no-op."""
    know = _key_knowledge(pkt.child)
    covered = jnp.all(pkt.ctxs == know, axis=-1) & jnp.all(
        know <= digest[None, :], axis=-1
    )
    keep = pkt.valid & ~covered
    zero = lambda x: jnp.where(
        keep.reshape((-1,) + (1,) * (x.ndim - 1)), x, jnp.zeros_like(x)
    )
    return pkt._replace(
        valid=keep,
        child=jax.tree.map(zero, pkt.child),
        ctxs=jnp.where(keep[:, None], pkt.ctxs, 0),
    )


def mesh_delta_gossip_map(
    state: MapState,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
    telemetry: bool = False,
    pipeline: bool = True,
    digest: bool = True,
    donate: bool = False,
    faults=None,
    ack_window=False,
    wal=None,
    fused: bool = True,
):
    """Ring δ anti-entropy for Map<K, MVReg> replica batches over the
    mesh — the bandwidth-bounded mode for large key universes with local
    churn (see delta.mesh_delta_gossip for semantics, the ROUNDS BUDGET
    warning, and the top-closure step). Returns
    ``(states [P, ...], dirty [P, K], overflow[2], residue)`` — residue
    is the runtime convergence indicator (0 = provably converged; see
    delta_ring.run_delta_ring)."""
    from .delta_ring import run_delta_ring

    state = pad_replicas_map(state, mesh.shape[REPLICA_AXIS])
    state = pad_keys(state, mesh.shape[ELEMENT_AXIS])
    pad_r = state.top.shape[0] - dirty.shape[0]
    pad_k = state.dkeys.shape[-1] - dirty.shape[-1]
    if pad_r or pad_k:  # zero-pad copies would defeat donation
        dirty = jnp.pad(dirty, ((0, pad_r), (0, pad_k)))
        fctx = jnp.pad(fctx, ((0, pad_r), (0, pad_k), (0, 0)))

    def close_top(folded: MapState, top: jax.Array) -> MapState:
        """Adopt the mesh-wide top and re-replay parked keyset-removes
        under it (delta_ring documents why)."""
        folded = _drop_stale_deferred(_apply_parked(folded._replace(top=top)))
        return folded._replace(child=_canon_child(folded.child))

    return run_delta_ring(
        "map_delta_gossip", state, dirty, fctx, mesh, rounds, cap,
        specs=map_specs(),
        local_fold=map_ops.fold,
        extract=extract_delta_map,
        apply_fn=apply_delta_map,
        close_top=close_top,
        telemetry=telemetry, slots_fn=map_ops.changed_keys,
        pipeline=pipeline, digest=digest, gate=gate_delta_map,
        donate=donate, faults=faults, ack_window=ack_window,
        wal=wal, wal_kind="map", fused=fused,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _register():
    from ..analysis import gate_states as gs
    from .delta import _reg_delta_ep

    _reg_delta_ep(
        "mesh_delta_gossip_map", "map_delta_gossip", gs.mk_map, gs.GE,
        lambda s, d, f, mesh: mesh_delta_gossip_map(
            s, d, f, mesh, donate=True
        ),
    )

    from ..analysis.registry import register_fault_surface

    register_fault_surface("mesh_delta_gossip_map", module=__name__)

_register()
