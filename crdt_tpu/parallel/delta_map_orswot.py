"""δ-state anti-entropy for ``Map<K, Orswot<M>>``.

The slab-composition invariant makes this nearly free: a MapOrswotState
IS a flat orswot over the K×M product space plus one outer keyset-
remove buffer (ops/map_orswot.py). The delta packet is therefore
delta.py's (element row, per-row context) machinery on the core — rows
at (key, member) granularity — with the outer parked keyset buffer
riding whole next to the leaf buffer, replayed and dead-key-scrubbed at
apply time exactly as ``mo_ops.join`` does.

Tracking contract as in delta.py (op granularity): an inner add/rm
marks its (key, member) rows; an outer keyset-remove marks the key's
whole row block with its (key-scoped) clock.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops import map_orswot as mo_ops
from ..ops.map_orswot import MapOrswotState
from ..ops.outer_level import concat_outer, settle_outer_level
from .delta import (
    DeltaPacket,
    apply_delta,
    close_top_orswot,
    extract_delta,
    interval_accumulate,
)
from .mesh import ELEMENT_AXIS, REPLICA_AXIS, map_orswot_specs, pad_map_orswot


class MapOrswotDeltaPacket(NamedTuple):
    """delta.py's row packet on the K×M core + the outer keyset buffer."""

    core: DeltaPacket
    kdcl: jax.Array    # [D, A]
    kdkeys: jax.Array  # [D, K]
    kdvalid: jax.Array # [D]


def interval_accumulate_mo(
    dirty: jax.Array, fctx: jax.Array, old: MapOrswotState, new: MapOrswotState
) -> Tuple[jax.Array, jax.Array]:
    """delta.interval_accumulate on the flat core (rows are (key, member)
    cells, so endpoint diffs capture inner adds/removes AND outer
    keyset-removes — both only ever change core rows)."""
    return interval_accumulate(dirty, fctx, old.core, new.core)


def extract_delta_mo(
    state: MapOrswotState, dirty: jax.Array, fctx: jax.Array, cap: int, start=0
) -> Tuple[MapOrswotDeltaPacket, jax.Array, jax.Array]:
    core_pkt, dirty, fctx = extract_delta(state.core, dirty, fctx, cap, start)
    return (
        MapOrswotDeltaPacket(
            core=core_pkt,
            kdcl=state.kdcl,
            kdkeys=state.kdkeys,
            kdvalid=state.kdvalid,
        ),
        dirty,
        fctx,
    )


def apply_delta_mo(
    state: MapOrswotState,
    pkt: MapOrswotDeltaPacket,
    dirty: jax.Array,
    fctx: jax.Array,
    element_axis=None,
):
    """Core row-join via delta.apply_delta, then the outer keyset level:
    union/replay/compact the kd buffer (mo_ops' settle semantics) and
    scrub parked state inside bottomed keys. Returns
    ``(state, dirty, fctx, overflow[2])`` — [inner, outer] as in
    mo_ops.join."""
    core, dirty, fctx, inner_of = apply_delta(state.core, pkt.core, dirty, fctx)

    before = core.ctr
    st = MapOrswotState(
        core,
        *concat_outer(
            (state.kdcl, state.kdkeys, state.kdvalid),
            (pkt.kdcl, pkt.kdkeys, pkt.kdvalid),
        ),
    )
    st, outer_of = settle_outer_level(
        st,
        state.kdcl.shape[-2],
        get_bufs=lambda s: (s.kdcl, s.kdkeys, s.kdvalid),
        with_bufs=lambda s, cl, ks, v: s._replace(kdcl=cl, kdkeys=ks, kdvalid=v),
        replay=mo_ops._replay_outer,
        scrub=mo_ops._scrub_dead_keys,
        element_axis=element_axis,
    )
    # Rows the outer replay killed forward their pre-replay knowledge
    # (the delta.py invariant); the kd slots themselves ride every
    # packet, so the removal clocks propagate regardless.
    replay_changed = jnp.any(st.core.ctr != before, axis=-1)
    dirty = dirty | replay_changed
    fctx = jnp.maximum(fctx, jnp.where(replay_changed[:, None], before, 0))
    return st, dirty, fctx, jnp.stack([jnp.any(inner_of), outer_of])


def mesh_delta_gossip_map_orswot(
    state: MapOrswotState,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
):
    """Ring δ anti-entropy for Map<K, Orswot> replica batches (see
    delta.mesh_delta_gossip for semantics and budgeting). ``dirty`` /
    ``fctx`` are at (key, member) cell granularity over K×M. Returns
    ``(states [P, ...], dirty, overflow[2])``."""
    from functools import partial

    from .delta_ring import run_delta_ring

    state = pad_map_orswot(
        state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS]
    )
    pad_r = state.core.top.shape[0] - dirty.shape[0]
    pad_e = state.core.ctr.shape[-2] - dirty.shape[-1]
    dirty = jnp.pad(dirty, ((0, pad_r), (0, pad_e)))
    fctx = jnp.pad(fctx, ((0, pad_r), (0, pad_e), (0, 0)))

    def close_top(folded: MapOrswotState, top: jax.Array) -> MapOrswotState:
        core = close_top_orswot(folded.core, top)
        # _replay_outer also drops outer slots the new top caught up to;
        # slot liveness must stay replicated across element shards.
        st = mo_ops._replay_outer(folded._replace(core=core))
        return mo_ops._scrub_dead_keys(st, element_axis=ELEMENT_AXIS)

    return run_delta_ring(
        "map_orswot_delta_gossip", state, dirty, fctx, mesh, rounds, cap,
        specs=map_orswot_specs(),
        local_fold=partial(mo_ops.fold, element_axis=ELEMENT_AXIS),
        extract=extract_delta_mo,
        apply_fn=partial(apply_delta_mo, element_axis=ELEMENT_AXIS),
        close_top=close_top,
        top_of=lambda s: s.core.top,
    )
