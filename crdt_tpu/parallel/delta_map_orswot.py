"""δ-state anti-entropy for ``Map<K, Orswot<M>>``.

The slab-composition invariant makes this nearly free: a MapOrswotState
IS a flat orswot over the K×M product space plus one outer keyset-
remove buffer (ops/map_orswot.py). The delta packet is therefore
delta.py's (element row, per-row context) machinery on the core — rows
at (key, member) granularity — with the outer parked keyset buffer
riding whole next to the leaf buffer, replayed and dead-key-scrubbed at
apply time exactly as ``mo_ops.join`` does. The wrapping itself is one
application of ``delta_nest.nested_delta`` (the δ induction step).

Tracking contract as in delta.py (op granularity): an inner add/rm
marks its (key, member) rows; an outer keyset-remove marks the key's
whole row block with its (key-scoped) clock.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops import map_orswot as mo_ops
from ..ops.map_orswot import MapOrswotState
from ..ops.orswot import changed_members
from .delta import (
    DeltaPacket,
    apply_delta,
    extract_delta,
    gate_delta,
    interval_accumulate,
)
from .delta_nest import close_top_nested, nested_delta, nested_gate
from .mesh import ELEMENT_AXIS, REPLICA_AXIS, map_orswot_specs, pad_map_orswot


class MapOrswotDeltaPacket(NamedTuple):
    """delta.py's row packet on the K×M core + the outer keyset buffer."""

    core: DeltaPacket
    kdcl: jax.Array    # [D, A]
    kdkeys: jax.Array  # [D, K]
    kdvalid: jax.Array # [D]


def interval_accumulate_mo(
    dirty: jax.Array, fctx: jax.Array, old: MapOrswotState, new: MapOrswotState
) -> Tuple[jax.Array, jax.Array]:
    """delta.interval_accumulate on the flat core (rows are (key, member)
    cells, so endpoint diffs capture inner adds/removes AND outer
    keyset-removes — both only ever change core rows)."""
    return interval_accumulate(dirty, fctx, old.core, new.core)


extract_delta_mo, apply_delta_mo = nested_delta(
    mo_ops.LEVEL,
    extract_delta,
    lambda s, p, d, f, element_axis=None: apply_delta(s, p, d, f),
    packet_cls=MapOrswotDeltaPacket,
)
gate_delta_mo = nested_gate(gate_delta, MapOrswotDeltaPacket)


def mesh_delta_gossip_map_orswot(
    state: MapOrswotState,
    dirty: jax.Array,
    fctx: jax.Array,
    mesh: Mesh,
    rounds: Optional[int] = None,
    cap: int = 64,
    telemetry: bool = False,
    pipeline: bool = True,
    digest: bool = True,
    donate: bool = False,
    faults=None,
    ack_window=False,
    wal=None,
    fused: bool = True,
):
    """Ring δ anti-entropy for Map<K, Orswot> replica batches (see
    delta.mesh_delta_gossip for semantics and the ROUNDS BUDGET
    warning). ``dirty`` / ``fctx`` are at (key, member) cell granularity
    over K×M. Returns ``(states [P, ...], dirty, overflow[2], residue)``
    — residue is the runtime convergence indicator (0 = provably
    converged; see delta_ring.run_delta_ring)."""
    from .delta_ring import run_delta_ring

    state = pad_map_orswot(
        state, mesh.shape[REPLICA_AXIS], mesh.shape[ELEMENT_AXIS]
    )
    pad_r = state.core.top.shape[0] - dirty.shape[0]
    pad_e = state.core.ctr.shape[-2] - dirty.shape[-1]
    if pad_r or pad_e:  # zero-pad copies would defeat donation
        dirty = jnp.pad(dirty, ((0, pad_r), (0, pad_e)))
        fctx = jnp.pad(fctx, ((0, pad_r), (0, pad_e), (0, 0)))

    return run_delta_ring(
        "map_orswot_delta_gossip", state, dirty, fctx, mesh, rounds, cap,
        specs=map_orswot_specs(),
        local_fold=partial(mo_ops.fold, element_axis=ELEMENT_AXIS),
        extract=extract_delta_mo,
        apply_fn=partial(apply_delta_mo, element_axis=ELEMENT_AXIS),
        close_top=partial(
            close_top_nested, mo_ops.LEVEL, element_axis=ELEMENT_AXIS
        ),
        top_of=lambda s: s.core.top,
        telemetry=telemetry,
        slots_fn=lambda a, b: changed_members(a.core, b.core),
        pipeline=pipeline, digest=digest, gate=gate_delta_mo,
        donate=donate, faults=faults, ack_window=ack_window,
        wal=wal, wal_kind="map_orswot", fused=fused,
    )


# ---- static-analysis registration (crdt_tpu.analysis) --------------------

def _register():
    from ..analysis import gate_states as gs
    from .delta import _reg_delta_ep

    _reg_delta_ep(
        "mesh_delta_gossip_map_orswot", "map_orswot_delta_gossip",
        gs.mk_map_orswot, gs.GK1 * gs.GM,
        lambda s, d, f, mesh: mesh_delta_gossip_map_orswot(
            s, d, f, mesh, donate=True
        ),
    )

    from ..analysis.registry import register_fault_surface

    register_fault_surface("mesh_delta_gossip_map_orswot", module=__name__)

_register()
