"""The δ-state induction step, as code — the delta-side twin of
``ops.nest.NestLevel``.

Every nesting level wraps the core's delta machinery the same way:
packets gain the level's whole (bounded) parked-keyset buffer, apply
joins the core delta then settles the level's buffer (union → dedupe →
replay → compact → scrub), and rows the level's replay killed forward
their pre-replay knowledge (the delta.py invariant). Through round 3
that was two hand-written flavors (delta_map_orswot.py, delta_map3.py)
that had to be patched in lockstep (commit 8025404 touched all delta
files at once — the hazard the combinator removes). Depth N needs no
new flavor: ``nested_delta(level, *nested_delta(inner, leaf_extract,
leaf_apply))`` composes, and the depth-4 gate in
tests/test_nest_depth4.py runs exactly that.

Only orswot-leaf chains close generically (``close_top_nested`` ends in
delta.close_top_orswot); the Map<K, MVReg> leaf flavor (delta_map.py)
has slot-table packets and its own closure — it is a *leaf*, not an
induction instance.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.nest import NestLevel
from .delta import close_top_orswot


class NestedDeltaPacket(NamedTuple):
    """The core's delta packet + one level's parked-keyset buffer riding
    whole (bounded). Concrete flavors may substitute their own 4-field
    class (same positional layout) to keep public packet types stable."""

    core: Any
    dcl: jax.Array    # [D, A]
    dkeys: jax.Array  # [D, K]
    dvalid: jax.Array # [D]


def nested_delta(
    level: NestLevel,
    core_extract: Callable,
    core_apply: Callable,
    packet_cls=NestedDeltaPacket,
) -> Tuple[Callable, Callable]:
    """Wrap a core (extract, apply) delta pair with one nesting level.
    ``core_apply`` must accept ``(state, pkt, dirty, fctx,
    element_axis=None)``; adapt leaf appliers with a lambda. Returns the
    level's ``(extract, apply)`` pair with the same signatures, so the
    construction composes to any depth."""

    def extract(state, dirty, fctx, cap, start=0):
        core_pkt, dirty, fctx = core_extract(state[0], dirty, fctx, cap, start)
        return packet_cls(core_pkt, state[1], state[2], state[3]), dirty, fctx

    def apply_fn(state, pkt, dirty, fctx, element_axis=None):
        core, dirty, fctx, core_of = core_apply(
            state[0], pkt[0], dirty, fctx, element_axis
        )

        before = level.core.leaf_ctr(core)
        st = level._make(core, *level.concat_bufs(state, pkt))
        st, outer_of = level.settle_outer(
            st, state[1].shape[-2], element_axis
        )
        # Rows this level's replay killed forward their pre-replay
        # knowledge (the delta.py invariant); the parked slots
        # themselves ride every packet, so the removal clocks propagate
        # regardless.
        after = level.leaf_ctr(st)
        replay_changed = jnp.any(after != before, axis=-1)
        dirty = dirty | replay_changed
        fctx = jnp.maximum(
            fctx, jnp.where(replay_changed[:, None], before, 0)
        )
        return st, dirty, fctx, jnp.concatenate(
            [jnp.atleast_1d(core_of), outer_of[None]]
        )

    return extract, apply_fn


def nested_gate(core_gate: Callable, packet_cls=NestedDeltaPacket) -> Callable:
    """Lift a core digest gate through one nesting level: only the core
    packet's slots gate (delta.gate_delta documents the soundness
    argument); the level's parked-keyset buffer rides whole regardless
    — parked rm clocks are their own context and already carry a
    per-slot validity mask, so there is nothing further to gate."""

    def gate(pkt, digest):
        return packet_cls(core_gate(pkt[0], digest), *pkt[1:])

    return gate


def close_top_nested(level, folded, top, element_axis=None):
    """Adopt the mesh-wide top and re-replay parked removes at EVERY
    level, innermost first, then scrub (delta_ring documents why the
    closure is needed and sound). Orswot-leaf chains only."""

    def rec(lv, s):
        if isinstance(lv, NestLevel):
            core = rec(lv.core, s[0])
            return lv.replay_outer(lv._make(core, s[1], s[2], s[3]))
        return close_top_orswot(s, top)

    return level.scrub_self(rec(level, folded), element_axis)
