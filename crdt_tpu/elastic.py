"""Elastic capacity manager: live overflow → widen → resume migration.

Every bounded device structure surfaces overflow correctly
(``DeferredOverflow`` / ``DotCapacityOverflow`` / ``SlotOverflow`` /
a full interned universe's ``UniverseFull``) but, before this module, the
only remedy was "rebuild the model with a larger capacity" — a
long-lived replica that hit a cap mid-gossip was dead. This module is
the sanctioned recovery, the capacity analog of lifecycle.py's dtype
widening (VERDICT r5 Weak #6):

- :func:`widen` — grow named capacity axes (2× by default,
  policy-configurable) and re-encode the live device state into the
  wider layout via the per-kind ``widen`` kernels (``ops/orswot.py``,
  ``ops/sparse_orswot.py``, ``ops/sparse_mvmap.py``,
  ``ops/sparse_nest.py``, ``ops/mvreg.py`` through ``ops/map.py``) —
  pure tail padding for dense slabs, a monotone segment-table repack
  for sparse (no host round-trip either way). Delta-state semantics
  (Almeida et al.; Enes et al., PAPERS.md) guarantee the re-encoded
  state rejoins gossip and converges without replay: the migration is
  bit-identical to a from-scratch model built at the wider capacity,
  so every later join is the same lattice join.
- :func:`recover` / :func:`elastic_call` — the overflow→widen→resume
  loop: map a capacity error to the implicated axes, widen them, retry.
- :func:`widen_dtype` / :func:`migrate` — compose capacity growth with
  lifecycle-style u32→u64 counter widening in ONE migration (every
  uint32 plane of a causal state is a counter plane — ids are int32,
  masks bool — so the dtype migration is one dtype-gated tree map).
- :func:`utilization` / :func:`record_headroom` — per-kind headroom
  gauges (``elastic.<kind>.headroom.<axis>``) so operators see pressure
  BEFORE overflow; :func:`widen` feeds ``elastic.widen_events`` and
  ``elastic.migrated_bytes`` counters.
- :func:`shrink` / :class:`Hysteresis` — the INVERSE migration
  (reclaim/, ISSUE 5): per-kind ``narrow``/``narrow_span`` kernels
  slice dead tail lanes off (refused when occupancy does not fit),
  governed by a low-water hysteresis so widen/shrink cannot thrash;
  feeds ``reclaim.shrink_events`` / ``reclaim.reclaimed_bytes``.
  Run ``reclaim.compact_model`` first so retired parked slots and
  stale payload do not pin lanes.

Like lifecycle.py's migrations, widening is ADMINISTRATIVE: apply it
identically on every host holding the replica set. It commutes with
gossip (the widened state is bit-identical to a wider-born one), so a
replica may pause mid-round, migrate, and rejoin — the ring entry
points' elastic wrappers (parallel/anti_entropy.py ``gossip_elastic``,
parallel/delta_ring.py ``delta_gossip_elastic``) do exactly that.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from .models.orswot import BatchedOrswot, DeferredOverflow
from .models.registers import SlotOverflow
from .models.sparse_orswot import BatchedSparseOrswot, DotCapacityOverflow
from .utils.interner import UniverseFull
from .utils.metrics import metrics, state_nbytes


#: The errors :func:`elastic_call` treats as recoverable capacity
#: pressure. UniverseFull is the interner's full-universe signal
#: (utils/interner.py bounded_intern); a plain IndexError is a bug in
#: the caller's code and re-raises untouched.
CAPACITY_ERRORS = (
    DeferredOverflow, DotCapacityOverflow, SlotOverflow, UniverseFull
)


@dataclass(frozen=True)
class ElasticPolicy:
    """How aggressively to widen — and how cautiously to shrink.

    ``factor`` scales each implicated axis on widen (ceil, never less
    than +1 lane) and divides it on shrink; ``max_migrations`` bounds
    the overflow→widen→retry loop of :func:`elastic_call`.

    The shrink half (reclaim/, ISSUE 5) is deliberately hysteretic so
    widen/shrink cannot thrash: :class:`Hysteresis` shrinks an axis
    only after its occupancy sat below ``low_water`` for
    ``shrink_rounds`` CONSECUTIVE observations, never below
    ``shrink_floor`` lanes, and any widening resets the streak.

    The widen half (``high_water`` / ``widen_rounds``, ISSUE 11) makes
    the debounce SYMMETRIC for policy drivers that decide in both
    directions (``Hysteresis.vote`` — the scaleout Autoscaler's
    admit/drain governor): a pressure signal must sit at or above
    ``high_water`` for ``widen_rounds`` consecutive observations before
    a widen-direction decision fires. The original shrink-only fields
    keep their exact semantics — ``observe`` is unchanged."""

    factor: float = 2.0
    max_migrations: int = 4
    low_water: float = 0.25
    shrink_rounds: int = 4
    shrink_floor: int = 8
    high_water: float = 0.85
    widen_rounds: int = 2


DEFAULT_POLICY = ElasticPolicy()


# ---- per-kind axis tables -------------------------------------------------
# axis -> (capacity, used-thunk) getters; "used" is the live occupancy
# the headroom gauges report (interner length for universes, max live
# slots for device buffers). Occupancy is LAZY — it forces a device →
# host copy of the masks (and, for sparse maps, an O(live cells)
# unique), which capacity-only callers (widen, capacities) never need.

def _max_count(mask) -> int:
    a = np.asarray(mask)
    return int(a.sum(axis=-1).max()) if a.size else 0


def _max_listed(ids) -> int:
    a = np.asarray(ids)
    return int((a >= 0).sum(axis=-1).max()) if a.size else 0


def _axes_orswot(m) -> Dict[str, Tuple[int, Callable[[], int]]]:
    return {
        "n_members": (m.state.ctr.shape[-2], lambda: len(m.members)),
        "n_actors": (m.state.top.shape[-1], lambda: len(m.actors)),
        "deferred_cap": (
            m.state.dvalid.shape[-1], lambda: _max_count(m.state.dvalid)
        ),
    }


def _axes_sparse_orswot(m) -> Dict[str, Tuple[int, Callable[[], int]]]:
    return {
        "dot_cap": (m.state.eid.shape[-1], lambda: _max_count(m.state.valid)),
        "n_actors": (m.state.top.shape[-1], lambda: len(m.actors)),
        "deferred_cap": (
            m.state.dvalid.shape[-1], lambda: _max_count(m.state.dvalid)
        ),
        "rm_width": (
            m.state.didx.shape[-1], lambda: _max_listed(m.state.didx)
        ),
    }


def _axes_map(m) -> Dict[str, Tuple[int, Callable[[], int]]]:
    return {
        "n_keys": (m.state.dkeys.shape[-1], lambda: len(m.keys)),
        "n_actors": (m.state.top.shape[-1], lambda: len(m.actors)),
        "sibling_cap": (
            m.state.child.valid.shape[-1],
            lambda: _max_count(m.state.child.valid),
        ),
        "deferred_cap": (
            m.state.dvalid.shape[-1], lambda: _max_count(m.state.dvalid)
        ),
    }


def _axes_sparse_map(m) -> Dict[str, Tuple[int, Callable[[], int]]]:
    return {
        "cell_cap": (m.state.kid.shape[-1], lambda: _max_count(m.state.valid)),
        "n_keys": (m.n_keys, lambda: len(m.keys)),
        "n_actors": (m.state.top.shape[-1], lambda: len(m.actors)),
        "sibling_cap": (m.sibling_cap, lambda: _max_siblings(m.state)),
        "deferred_cap": (
            m.state.dvalid.shape[-1], lambda: _max_count(m.state.dvalid)
        ),
        "rm_width": (
            m.state.kidx.shape[-1], lambda: _max_listed(m.state.kidx)
        ),
    }


def _axes_sparse_nested(m) -> Dict[str, Tuple[int, Callable[[], int]]]:
    core = m.state.core
    return {
        "cell_cap": (core.kid.shape[-1], lambda: _max_count(core.valid)),
        "span": (m.span, lambda: len(m.keys2)),
        "n_keys1": (m.n_keys1, lambda: len(m.keys1)),
        "n_actors": (core.top.shape[-1], lambda: len(m.actors)),
        "sibling_cap": (m.sibling_cap, lambda: _max_siblings(core)),
        "deferred_cap": (
            core.dvalid.shape[-1], lambda: _max_count(core.dvalid)
        ),
        "rm_width": (core.kidx.shape[-1], lambda: _max_listed(core.kidx)),
        "key_deferred_cap": (
            m.state.kdvalid.shape[-1], lambda: _max_count(m.state.kdvalid)
        ),
        "key_rm_width": (
            m.state.kidx.shape[-1], lambda: _max_listed(m.state.kidx)
        ),
    }


def _max_siblings(core) -> int:
    """Max live cells sharing one (replica, key) — the sibling_cap
    occupancy. One vectorized unique over (row, kid) pairs: O(live
    cells) total with no per-replica Python loop (record_headroom runs
    at op/round cadence over bench-scale replica counts), and no dense
    bincount over the huge virtual key universe."""
    kid = np.asarray(core.kid).reshape(-1, core.kid.shape[-1])
    valid = np.asarray(core.valid).reshape(kid.shape)
    rows, _ = np.nonzero(valid)
    if not rows.size:
        return 0
    packed = rows.astype(np.int64) << 31 | kid[valid].astype(np.int64)
    return int(np.unique(packed, return_counts=True)[1].max())


def _kind_tables():
    from .models.map import BatchedMap
    from .models.sparse_mvmap import BatchedSparseMap
    from .models.sparse_nested_map import BatchedSparseNestedMap

    return {
        BatchedOrswot: ("orswot", _axes_orswot),
        BatchedSparseOrswot: ("sparse_orswot", _axes_sparse_orswot),
        BatchedMap: ("map", _axes_map),
        BatchedSparseMap: ("sparse_map", _axes_sparse_map),
        BatchedSparseNestedMap: ("sparse_nested_map", _axes_sparse_nested),
    }


def _lookup(model):
    for cls, entry in _kind_tables().items():
        if isinstance(model, cls):
            return entry
    raise TypeError(
        f"elastic migrations cover the batched set/map family, got "
        f"{type(model).__name__}"
    )


def kind_of(model) -> str:
    """The metrics namespace for a model (``orswot``, ``sparse_map``, …)."""
    return _lookup(model)[0]


def utilization(model) -> Dict[str, Tuple[int, int]]:
    """Per-axis ``(capacity, used)`` — the raw headroom table (forces
    the occupancy scan; capacity-only callers use :func:`capacities`)."""
    return {
        k: (cap, used()) for k, (cap, used) in _lookup(model)[1](model).items()
    }


def capacities(model) -> Dict[str, int]:
    """Current capacity per elastic axis — shape reads only, no
    device → host occupancy scan."""
    return {k: cap for k, (cap, _) in _lookup(model)[1](model).items()}


def record_headroom(model) -> Dict[str, float]:
    """Record per-axis FREE-fraction gauges
    (``elastic.<kind>.headroom.<axis>``; 0.0 = at capacity, the signal
    to widen before overflow) and return them. Call at op/round cadence
    — host-side only, zero jit impact (utils/metrics.py discipline)."""
    kind = kind_of(model)
    out = {}
    for axis, (cap, used) in utilization(model).items():
        free = 0.0 if cap <= 0 else max(0.0, 1.0 - used / cap)
        out[axis] = free
        metrics.observe(f"elastic.{kind}.headroom.{axis}", free)
    return out


# ---- the migration --------------------------------------------------------

def _grown(cap: int, factor: float) -> int:
    return max(int(math.ceil(cap * factor)), cap + 1)


def widen(
    model,
    axes: Optional[Tuple[str, ...]] = None,
    policy: ElasticPolicy = DEFAULT_POLICY,
    **explicit: int,
) -> Dict[str, int]:
    """Widen ``axes`` of ``model`` by ``policy.factor`` (or to the
    ``explicit`` values) and re-encode the live device state in place
    via the model's ``widen_capacity``. Returns the new capacities of
    the changed axes. Feeds ``elastic.widen_events`` (and the per-kind
    variant) plus ``elastic.migrated_bytes`` — the bytes of the
    re-encoded state — and refreshes the headroom gauges."""
    kind, table = _lookup(model)
    current = {k: cap for k, (cap, _) in table(model).items()}
    new = dict(explicit)
    for axis in axes or ():
        if axis not in current:
            raise ValueError(f"{kind} has no elastic axis {axis!r}")
        new.setdefault(axis, _grown(current[axis], policy.factor))
    if not new:
        raise ValueError("nothing to widen: pass axes and/or explicit caps")
    for axis in new:
        if axis not in current:
            raise ValueError(f"{kind} has no elastic axis {axis!r}")
    if "span" in new and new["span"] % current["span"]:
        # A span widening must keep key ids (aligned offsets).
        new["span"] = current["span"] * int(
            math.ceil(new["span"] / current["span"])
        )
    # Packing interactions (sparse cell keys fit int32, so growing
    # span/n_actors may force the VIRTUAL key-universe bound down) are
    # the model's own business: widen_capacity auto-clamps bounds the
    # caller did not pin and raises — never silently clamps — on
    # explicit ones.
    from .telemetry import span

    with span("elastic.widen", kind=kind, axes=sorted(new)):
        model.widen_capacity(**new)
    metrics.count("elastic.widen_events")
    metrics.count(f"elastic.widen_events.{kind}")
    metrics.count("elastic.migrated_bytes", state_nbytes(model.state))
    record_headroom(model)
    return new


# ---- the inverse migration (reclaim/, ISSUE 5) ----------------------------

def _shrink_target(cap: int, used: int, policy: ElasticPolicy) -> int:
    """Where one shrink step lands: one ``factor`` step down, but never
    below live occupancy or the policy floor."""
    return max(int(math.ceil(cap / policy.factor)), used, policy.shrink_floor)


def _narrowable_axes(model) -> Tuple[str, ...]:
    """The elastic axes this model's ``narrow_capacity`` accepts —
    axes it cannot narrow (e.g. the nested kind's ``n_keys1``, whose
    ids are pinned by packing) are simply not shrink candidates."""
    import inspect

    try:
        params = inspect.signature(model.narrow_capacity).parameters
    except (AttributeError, TypeError, ValueError):
        return ()
    return tuple(a for a in capacities(model) if a in params)


def shrink(
    model,
    axes: Optional[Tuple[str, ...]] = None,
    policy: ElasticPolicy = DEFAULT_POLICY,
    **explicit: int,
) -> Dict[str, int]:
    """The inverse of :func:`widen` — narrow ``axes`` by one
    ``policy.factor`` step (or to the ``explicit`` values), re-encoding
    the live device state in place via the model's ``narrow_capacity``
    (which REFUSES when occupancy does not fit — compaction first,
    ``reclaim.compact_model``, frees retired parked slots so they do
    not pin lanes). Axes already at occupancy/floor are skipped, not
    errors — steady-state callers ask every round. Returns the new
    capacities of the axes actually narrowed and feeds
    ``reclaim.shrink_events`` + ``reclaim.reclaimed_bytes``.

    Like widening, shrinking is ADMINISTRATIVE: apply it identically on
    every host holding the replica set. It commutes with gossip for the
    same reason widening does — the narrowed state is bit-identical to
    a narrower-born model holding the same dots (the tail lanes sliced
    off were dead), so every later join is the same lattice join."""
    kind, table = _lookup(model)
    util = {k: (cap, used()) for k, (cap, used) in table(model).items()}
    for axis in tuple(axes or ()) + tuple(explicit):
        if axis not in util:
            raise ValueError(f"{kind} has no elastic axis {axis!r}")
    new: Dict[str, int] = {}
    for axis in axes or ():
        cap, used = util[axis]
        target = _shrink_target(cap, used, policy)
        if target < cap:
            new[axis] = target
    for axis, target in explicit.items():
        cap, used = util[axis]
        if target > cap:
            # Same error surface as the ops narrow kernels: an explicit
            # target is the caller's claim, not a steady-state poll.
            raise ValueError(
                f"shrink cannot grow {axis}: {cap} -> {target}"
            )
        if target < cap:
            new[axis] = target  # narrow_capacity enforces occupancy fit
    if not new:
        return {}
    from .telemetry import span

    before = state_nbytes(model.state)
    with span("elastic.shrink", kind=kind, axes=sorted(new)):
        model.narrow_capacity(**new)
    freed = max(before - state_nbytes(model.state), 0)
    metrics.count("reclaim.shrink_events")
    metrics.count(f"reclaim.shrink_events.{kind}")
    metrics.count("reclaim.reclaimed_bytes", freed)
    record_headroom(model)
    return new


class Hysteresis:
    """The symmetric widen/shrink governor.

    The shrink half (reclaim/, the original contract): call
    :meth:`observe` once per gossip round and it narrows an axis only
    after occupancy sat below ``policy.low_water`` for
    ``policy.shrink_rounds`` CONSECUTIVE rounds — a single quiet round
    after a burst reclaims nothing, and a widening (capacity grew
    between observations) resets every streak, so the widen loop and
    the shrink loop cannot chase each other. Composes with
    ``gossip_elastic``/``delta_gossip_elastic`` via their ``reclaim=``
    parameter the same way widening composes via overflow recovery.

    The widen half (ISSUE 11): :meth:`vote` is the direction-symmetric
    debouncer over an arbitrary named pressure signal in [0, 1] —
    ``high_water``/``widen_rounds`` gate the widen direction exactly as
    ``low_water``/``shrink_rounds`` gate shrink. The scaleout
    Autoscaler (crdt_tpu/scaleout/autoscaler.py) keys admit/drain
    decisions on it; ``observe`` keeps its original shrink-only
    behavior bit-for-bit (pinned by tests/test_elastic.py)."""

    def __init__(self, policy: ElasticPolicy = DEFAULT_POLICY):
        self.policy = policy
        self._streak: Dict[str, int] = {}
        self._caps: Dict[str, int] = {}
        self._hot: Dict[str, int] = {}
        self._cold: Dict[str, int] = {}

    def observe(
        self, model, policy: Optional[ElasticPolicy] = None
    ) -> Dict[str, int]:
        """Record one round's occupancy; shrink and return the narrowed
        axes when the hysteresis clears (usually ``{}``)."""
        policy = policy or self.policy
        candidates = []
        narrowable = _narrowable_axes(model)
        for axis, (cap, used) in utilization(model).items():
            prev = self._caps.get(axis)
            if prev is not None and cap > prev:
                self._streak[axis] = 0  # widened since last round
            self._caps[axis] = cap
            shrinkable = (
                axis in narrowable
                and cap > 0
                and used / cap < policy.low_water
                and _shrink_target(cap, used, policy) < cap
            )
            if shrinkable:
                self._streak[axis] = self._streak.get(axis, 0) + 1
            else:
                self._streak[axis] = 0
            if self._streak[axis] >= policy.shrink_rounds:
                candidates.append(axis)
        if not candidates:
            return {}
        shrunk = shrink(model, tuple(candidates), policy)
        for axis in shrunk:
            self._streak[axis] = 0
            self._caps[axis] = capacities(model)[axis]
        return shrunk

    def vote(
        self,
        name: str,
        pressure: float,
        policy: Optional[ElasticPolicy] = None,
    ) -> Optional[str]:
        """One debounced decision on a named pressure signal in [0, 1]:
        returns ``"widen"`` after ``pressure >= high_water`` held for
        ``widen_rounds`` CONSECUTIVE calls, ``"shrink"`` after
        ``pressure < low_water`` held for ``shrink_rounds``, else
        ``None``. A mid-band or opposite-direction observation resets
        BOTH streaks, and a fired vote resets its own — the debounce
        re-arms, so a driver acting on the vote (the Autoscaler's
        admit/drain) is never retriggered within the same debounce
        window, while a plateau that PERSISTS past another full window
        fires again (the driver absorbed one capacity move and the
        pressure still stands — more moves are warranted). Signals are
        independent per ``name`` (one governor can debounce several
        meshes/axes)."""
        policy = policy or self.policy
        if not 0.0 <= pressure <= 1.0:
            raise ValueError(f"pressure {pressure} not in [0, 1]")
        hot = self._hot.get(name, 0)
        cold = self._cold.get(name, 0)
        if pressure >= policy.high_water:
            hot, cold = hot + 1, 0
        elif pressure < policy.low_water:
            hot, cold = 0, cold + 1
        else:
            hot = cold = 0
        decision = None
        if hot >= policy.widen_rounds:
            decision, hot = "widen", 0
        elif cold >= policy.shrink_rounds:
            decision, cold = "shrink", 0
        self._hot[name], self._cold[name] = hot, cold
        if decision is not None:
            from . import obs

            obs.emit("elastic_vote", name=name, decision=decision,
                     pressure=float(pressure))
        return decision


def axes_for(model, exc: BaseException) -> Tuple[str, ...]:
    """The capacity axes a surfaced overflow implicates — the
    exception-type → axis mapping of the recovery loop. Empty tuple
    means the error is NOT elastic pressure (re-raise it)."""
    kind, table = _lookup(model)
    axes = table(model)  # caps + lazy occupancy; forced only below
    if isinstance(exc, DotCapacityOverflow):
        return ("dot_cap",) if "dot_cap" in axes else ("cell_cap",)
    if isinstance(exc, SlotOverflow):
        return ("sibling_cap",)
    if isinstance(exc, DeferredOverflow):
        # Slot-count overflows AND too-narrow parked keylist lanes
        # (rm_width) raise the same type; the message names the buffer,
        # but widening every parked axis the kind has is always sound
        # (monotone tail padding, bounded by max_migrations) and keeps
        # recovery independent of message text. The nested kind adds
        # its outer-level pair for the same reason.
        return tuple(
            a for a in (
                "deferred_cap", "rm_width",
                "key_deferred_cap", "key_rm_width",
            )
            if a in axes
        )
    if isinstance(exc, UniverseFull):
        # bounded_intern: implicate exactly the full universes.
        full = tuple(
            axis for axis in (
                "n_members", "n_actors", "n_keys", "n_keys1", "span"
            )
            if axis in axes and axes[axis][1]() >= axes[axis][0]
        )
        return full
    return ()


def recover(
    model, exc: BaseException, policy: ElasticPolicy = DEFAULT_POLICY
) -> Dict[str, int]:
    """Widen the axes ``exc`` implicates. Re-raises ``exc`` when it is
    not recoverable capacity pressure."""
    axes = axes_for(model, exc)
    if not axes:
        raise exc
    return widen(model, axes, policy)


def elastic_call(
    fn: Callable[[], object],
    model,
    policy: ElasticPolicy = DEFAULT_POLICY,
):
    """The overflow→widen→resume loop: run ``fn`` (an op application, a
    merge, a fold — any closure over ``model``), and on a capacity
    error widen the implicated axes and retry, up to
    ``policy.max_migrations`` migrations. Sound because every rejected
    operation is side-effect free (the validation.py contract: ops roll
    back interner allocations; joins raise without committing), so the
    retry replays against an unchanged — merely wider — state."""
    for _ in range(policy.max_migrations):
        try:
            return fn()
        except CAPACITY_ERRORS as exc:
            recover(model, exc, policy)
    return fn()


# ---- dtype composition (lifecycle.py's widening, generalized) -------------

def widen_dtype(model, dtype: str = "uint64") -> None:
    """u32 → u64 counter-plane widening for the causal set/map family —
    the lifecycle.py ``widen_counters`` analog (same x64 guard, same
    bit-identical contract: every counter VALUE is preserved, only the
    ceiling lifts). Every uint32 plane of a causal state is a counter
    plane (top/birth/write clocks and witness counters; ids are int32,
    masks bool), so the migration is one dtype-gated tree map."""
    import jax
    import jax.numpy as jnp

    target = jnp.dtype(dtype)
    if target == jnp.dtype("uint64") and not jax.config.jax_enable_x64:
        raise RuntimeError(
            "uint64 lanes require x64 mode: call "
            "configure(counter_dtype='uint64') before widening"
        )
    _lookup(model)  # covered-family check
    model.state = jax.tree.map(
        lambda x: x.astype(target) if x.dtype == jnp.dtype("uint32") else x,
        model.state,
    )


def migrate(
    model,
    counter_dtype: Optional[str] = None,
    axes: Optional[Tuple[str, ...]] = None,
    policy: ElasticPolicy = DEFAULT_POLICY,
    **explicit: int,
) -> Dict[str, int]:
    """One administrative migration composing both widenings: grow
    capacity axes AND (optionally) the counter dtype — e.g. u32→u64 +
    capacity 2× in one step. Order matters only for efficiency: dtype
    first, so the capacity padding allocates wide lanes once."""
    if counter_dtype is not None:
        widen_dtype(model, counter_dtype)
    if axes or explicit:
        return widen(model, axes, policy, **explicit)
    record_headroom(model)
    return {}


from .analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev("elastic_vote", subsystem="elastic",
        fields=("name", "decision", "pressure"), module=__name__)


__all__ = [
    "CAPACITY_ERRORS", "DEFAULT_POLICY", "ElasticPolicy", "Hysteresis",
    "axes_for", "capacities", "elastic_call", "kind_of", "migrate",
    "record_headroom", "recover", "shrink", "utilization", "widen",
    "widen_dtype",
]
