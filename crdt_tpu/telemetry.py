"""jit-transparent telemetry: on-device convergence counters + spans.

The host-side registry (utils/metrics.py) goes blind exactly where
production traffic lives — inside jit, ``deferred_depth`` returns the
-1 traced sentinel and a fully jitted train/serve step records nothing.
This module is the device-side complement: a :class:`Telemetry` pytree
sidecar computed **in-kernel** with ``lax`` ops, so it survives
jit/shard_map, accumulates across gossip rounds, and returns alongside
state from the mesh entry points (``mesh_gossip*`` / ``mesh_fold*`` /
``run_delta_ring`` / ``gossip_elastic``) behind a ``telemetry=`` flag
that defaults off and traces NOTHING when disabled (the telemetry=False
program lowers to HLO identical to the flag-free one —
tests/test_telemetry.py pins this by ``lower().as_text()`` comparison).

The counters are the headline evaluation quantities of the δ-CRDT
literature (Almeida et al. 1603.01529; Enes et al. 1803.02750 — bytes
shipped and sync metadata per round), measured natively per round:

- ``merges``          — pairwise lattice-join applications (local fold
  joins, nominally rows-1, plus one per ring round per replica rank;
  all-reduce entry points count log2(P) / P-1 exchange joins),
  summed over replica ranks.
- ``slots_changed``   — content lanes the cross-replica joins actually
  changed (per-kind definition: dense ORSWOT members whose birth
  clocks changed, map keys whose cells changed, sparse dot/cell lanes
  changed; the generic fallback diffs every state plane).
- ``deferred_depth``  — final parked-slot depth: max over replicas of
  valid slots summed across every ``*dvalid`` buffer level (the same
  masked-epoch convention ``metrics.deferred_depth`` walks on host).
- ``bytes_exchanged`` — physical WIRE bytes shipped over mesh links:
  the per-device shipped pytree's STATIC bytes × exchanges, summed over
  ALL devices (element-axis copies each really transmit). Padded /
  invalid packet lanes count — this is what the links carry.
- ``bytes_useful``    — post-mask PAYLOAD bytes: only the packet lanes
  whose validity masks survive (δ-ring slot ``valid`` and parked
  ``*dvalid`` masks — :func:`packet_useful_bytes`), so digest gating's
  byte win is visible next to the unchanged wire count. Non-δ entry
  points ship whole states with no mask and report wire == useful.
- ``residue``         — the δ-ring convergence indicator
  (parallel/delta_ring.py); 0 for non-δ entry points.
- ``widen_pressure``  — max occupancy fraction over the bounded parked
  buffers (1.0 = at capacity: the in-jit analog of the
  ``elastic.<kind>.headroom`` gauges, which report 1 - this).
- ``reclaimed_slots`` / ``reclaimed_bytes`` — lanes retired and their
  static bytes discarded by in-kernel causal-stability compaction
  (reclaim/; populated by the ``stability=`` flag on the gossip entry
  points, 0 elsewhere — host-side reclamation paths count under the
  same names in the registry via ``reclaim.record_reclaim``).
- ``frontier_lag``    — max over replicas/actor lanes of
  ``top - stable_frontier`` (0 = fully stable mesh); a lag growing
  under steady traffic means a straggler is pinning the frontier and
  reclamation has stalled (reclaim/frontier.py).
- ``stream_blocks`` / ``stream_staged_bytes`` / ``stream_overlap_hit``
  — the replica-streaming fold's accounting (parallel/stream.py; the
  registry twins are ``stream.blocks`` / ``stream.staged_bytes`` /
  ``stream.overlap_hit``): blocks streamed through the accumulator,
  total bytes staged into device memory for them, and stagings whose
  upload was issued while the previous block's join was still in
  flight (the double-buffer overlap actually landing). Filled by the
  stream driver host-side — the per-block loop lives outside the
  kernels — and 0 on every non-streaming entry point.
- ``faults_dropped`` / ``faults_rejected`` / ``faults_delayed`` — the
  degraded-mesh accounting (crdt_tpu/faults/; registry twins
  ``telemetry.<kind>.faults.packets_*``): packets lost on an injected
  link drop, packets REJECTED by the in-kernel checksum lane
  (integrity.py — corrupted content is never joined), and packets the
  link held one round. Populated by the ``faults=`` flag on the mesh
  entry points, 0 elsewhere.
- ``bytes_acked_skipped`` / ``ack_window_depth`` — the ack-window
  accounting (crdt_tpu/delta_opt/ackwin.py; registry twins
  ``delta_opt.acked_skipped[.kind]``): payload bytes the per-link
  acked-interval window masked off the δ rings (the back-propagation
  win ON TOP of digest gating — ``bytes_useful`` already reflects it,
  this field attributes it), and the max per-device count of rows with
  a live acked watermark at run end. Populated by ``ack_window=True``
  on ``run_delta_ring`` and the ``mesh_delta_gossip*`` family, 0
  elsewhere.
- ``wal_bytes`` / ``wal_fsyncs`` / ``snapshots_written`` /
  ``replayed_records`` / ``torn_tail_truncated`` / ``recovery_rounds``
  — the crash-consistent durability accounting (crdt_tpu/durability/;
  registry twins ``durability.*``): δ-record payload bytes appended to
  the write-ahead log and fsync barriers issued for them (populated
  host-side by the ``wal=`` flag on the δ-ring entries and
  ``mesh_stream_fold*`` — the append loop lives outside the kernels,
  the ``stream_*`` discipline), snapshot generations committed, WAL
  records replayed by a recovery, torn/corrupt log tails truncated on
  open, and recovery passes completed. 0 on every non-durable run.
- ``live_ranks`` / ``scaleout_admits`` / ``scaleout_drains`` /
  ``bootstrap_bytes`` — the elastic mesh scale-out accounting
  (crdt_tpu/scaleout/; registry twins ``scaleout.admits`` /
  ``scaleout.drains`` / ``scaleout.bootstrap_bytes``): admitted ranks
  on the replica axis (a gauge — the mesh's current serving width),
  live rank joins completed, graceful drains whose drain-complete
  certificate was issued, and newcomer-bootstrap wire bytes (including
  fault re-ships). Filled host-side by ``ScaleoutMesh.annotate`` — the
  membership loop lives outside the kernels, the ``stream_*``/``wal_*``
  discipline — and 0 on every fixed-width run.

- ``wire_packed_bytes`` — the fused wire path's POST-PACKING byte
  count (crdt_tpu/parallel/wire.py; registry twins
  ``wire.packed_bytes[.kind]``): nonzero u32 words actually occupied
  on the bit-packed wire (bitmaps + u16-pair ids + watermark-encoded
  clock lanes), the bytes a zero-suppressing transport would carry —
  reported NEXT to ``bytes_exchanged`` (the static wire shape) and
  ``bytes_useful`` (the post-mask raw payload) so the packing win is
  attributable. 0 on every ``fused=False`` or non-δ run.

- ``live_tenants`` / ``evicted_tenants`` / ``ingest_coalesced_ops`` /
  ``hist_ingest_batch`` — the multi-tenant serving accounting
  (crdt_tpu/serve/; registry twins
  ``telemetry.<kind>.serve.ingest_coalesced_ops`` plus
  ``live_tenants``/``evicted_tenants`` gauges): the SERVED tenant
  population (every session the front door answers for — device
  residency may be far smaller under the lane indirection; the
  resident count rides the ``serve.*`` registry counters) and tenants
  currently parked in the durable tier (gauges, filled host-side by
  ``Superblock.annotate``), ops that shared an ingest slab lane with a
  predecessor (each one a device dispatch the coalescing queue
  amortized away), and the per-flush applied-batch-size distribution
  (``IngestQueue.annotate`` — the ``stream_*``/``wal_*`` host-side
  fill discipline; 0/empty on every non-serving run).
- ``subscribers_live`` / ``cohorts_per_dispatch`` /
  ``delta_push_bytes`` / ``resync_fallbacks`` / ``hist_push_bytes`` —
  the δ-subscription fan-out accounting (crdt_tpu/fanout/; registry
  twins ``telemetry.<kind>.fanout.*`` plus a ``subscribers_live``
  gauge): live registered subscribers (a gauge, filled host-side by
  ``FanoutPlane.annotate``), watermark cohorts decomposed per push
  dispatch (each one a shared δ-decompose amortized over its whole
  cohort), δ payload bytes actually pushed to subscribers (post
  zero-suppression — the bytes a thin client's wire carries), pushes
  that degraded to the snapshot+suffix bootstrap resync instead of a
  δ (slow/dead subscribers — scaleout/bootstrap.py), and the
  per-cohort push-bytes distribution (in-kernel, riding the
  ``mesh_fanout_push`` telemetry branch). 0/empty on every
  non-fan-out run.
- ``serve_wal_bytes`` / ``serve_overlap_hit`` / ``rebalance_moves`` /
  ``hist_persist_us`` — the pipelined serving-loop accounting
  (crdt_tpu/serve/wal.py, loop.py, shard.py; registry twins
  ``telemetry.<kind>.serve.wal_bytes`` / ``.serve.overlap_hit`` /
  ``.serve.rebalance_moves``): dirty-tenant WAL bytes group-committed
  ahead of the dispatches (the durability cost of the
  log-before-dispatch ack), pipelined rounds whose slab assembly + WAL
  append genuinely hid in-flight device time (the serving twin of
  ``stream_overlap_hit``), skew-driven shard-map override moves
  applied by ``apply_rebalance``, and the per-row background-persist
  wall-clock distribution (``BackgroundPersister`` — the persists the
  pipeline moved OFF the dispatch latency path). Filled host-side by
  ``IngestQueue.annotate`` / ``ServeLoop.annotate``; 0/empty on every
  non-serving run.
- ``regions_live`` / ``geo_home_tenants`` / ``geo_exchanges`` /
  ``geo_exchange_bytes`` / ``geo_full_mirror_bytes`` /
  ``geo_failovers`` / ``hist_geo_watermark_lag`` — the geo-federation
  accounting (crdt_tpu/geo/; registry twins
  ``telemetry.<kind>.geo.*`` plus ``regions_live``/
  ``geo_home_tenants`` gauges): live federation regions and tenants
  homed across them (gauges, filled host-side by
  ``Federation.annotate``), cross-region anti-entropy rounds
  completed, the δ-lane wire bytes those rounds actually shipped NEXT
  to the full-state mirroring baseline they undercut (the
  partial-replication economics, attributable per run), region-kill
  re-homings completed, and the per-read mirror watermark-lag
  distribution (geo/reads.py certificates — how stale local reads
  actually ran). 0/empty on every non-federated run.
- ``hist_residue`` / ``hist_useful_bytes`` / ``hist_ack_depth`` /
  ``hist_packed_bytes`` / ``hist_dispatch_us`` — the in-kernel
  DISTRIBUTIONS
  (crdt_tpu/obs/hist.py :class:`~crdt_tpu.obs.hist.Hist` subtrees:
  log2 bucket counts + exact total; registry summary twins
  ``telemetry.<kind>.hist.<name>.p50/p95/p99`` plus per-bucket
  counters): per-round per-device unshipped-backlog rows (the residue
  quantity, observed EVERY ring round inside the loop carry),
  per-round post-mask payload bytes (digest + ack-window gating's
  round-shape, not just its total), per-round ack-window depth
  (``ack_window=True`` only), and host-timed per-dispatch wall-clock
  in MICROSECONDS (filled at the host boundary by
  :func:`time_dispatch` — the ``stream_*``/``wal_*`` discipline;
  includes compile time on a cold jit cache). The first three
  accumulate lax-only in the δ-ring loop, so they survive jit and
  shard_map and psum across the mesh like every scalar counter;
  non-δ entry points leave them empty.

Every non-histogram field is a replicated scalar, so the whole pytree
costs one word of output per field (plus one 32-lane counter plane per
histogram) and no extra collectives beyond one psum/pmax fusion group.

Span tracing (:func:`span`) is the host-side half: a context manager
that emits structured JSONL trace events (``configure_tracing`` points
them at a file; ``drain_events`` empties the in-memory ring) and nests
``jax.named_scope`` + ``jax.profiler.TraceAnnotation`` so the same
span names appear in XProf device timelines. Exporting both worlds —
registry snapshots, Telemetry pytrees, spans — to Prometheus text and
JSONL lives in :mod:`crdt_tpu.exporter`.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .obs import hist as obs_hist
from .utils.metrics import metrics


class Telemetry(NamedTuple):
    """On-device convergence counters (a pytree of replicated scalars)."""

    merges: jax.Array          # uint32 — join applications
    slots_changed: jax.Array   # uint32 — content lanes changed by joins
    deferred_depth: jax.Array  # uint32 — final max parked-slot depth
    bytes_exchanged: jax.Array # float32 — physical WIRE bytes over links
    bytes_useful: jax.Array    # float32 — post-mask payload bytes
    residue: jax.Array         # int32 — δ-ring residue (0 elsewhere)
    widen_pressure: jax.Array  # float32 — max parked-buffer occupancy
    reclaimed_slots: jax.Array # uint32 — lanes retired by compaction
    reclaimed_bytes: jax.Array # float32 — static bytes those lanes held
    frontier_lag: jax.Array    # uint32 — max(top - stable frontier)
    stream_blocks: jax.Array   # uint32 — replica blocks streamed
    stream_staged_bytes: jax.Array # float32 — bytes staged for blocks
    stream_overlap_hit: jax.Array  # uint32 — overlapped block uploads
    faults_dropped: jax.Array  # uint32 — packets lost to injected drops
    faults_rejected: jax.Array # uint32 — packets failing the checksum lane
    faults_delayed: jax.Array  # uint32 — packets held one round by a link
    bytes_acked_skipped: jax.Array # float32 — δ bytes the ack window masked
    ack_window_depth: jax.Array    # uint32 — max rows with a live ack mark
    wal_bytes: jax.Array           # float32 — δ-record bytes appended to WAL
    wal_fsyncs: jax.Array          # uint32 — fsync barriers for those appends
    snapshots_written: jax.Array   # uint32 — snapshot generations committed
    replayed_records: jax.Array    # uint32 — WAL records replayed on recovery
    torn_tail_truncated: jax.Array # uint32 — torn/corrupt WAL tails truncated
    recovery_rounds: jax.Array     # uint32 — recovery passes completed
    live_ranks: jax.Array          # uint32 — admitted ranks on the mesh axis
    scaleout_admits: jax.Array     # uint32 — live rank joins completed
    scaleout_drains: jax.Array     # uint32 — graceful drains certified
    bootstrap_bytes: jax.Array     # float32 — newcomer bootstrap wire bytes
    wire_packed_bytes: jax.Array   # float32 — post-packing bytes on the wire
    live_tenants: jax.Array        # uint32 — served tenant population
    evicted_tenants: jax.Array     # uint32 — tenants parked in the durable tier
    ingest_coalesced_ops: jax.Array  # uint32 — ops that shared a slab lane
    subscribers_live: jax.Array      # uint32 — live registered subscribers
    cohorts_per_dispatch: jax.Array  # uint32 — watermark cohorts decomposed
    delta_push_bytes: jax.Array      # float32 — δ bytes pushed to subscribers
    resync_fallbacks: jax.Array      # uint32 — pushes degraded to bootstrap
    serve_wal_bytes: jax.Array       # float32 — dirty-tenant WAL bytes appended
    serve_overlap_hit: jax.Array     # uint32 — pipelined rounds that hid device time
    rebalance_moves: jax.Array       # uint32 — skew-driven shard-map moves
    regions_live: jax.Array          # uint32 — live federation regions
    geo_home_tenants: jax.Array      # uint32 — tenants homed across live regions
    geo_exchanges: jax.Array         # uint32 — cross-region anti-entropy rounds
    geo_exchange_bytes: jax.Array    # float32 — δ bytes shipped cross-region
    geo_full_mirror_bytes: jax.Array # float32 — full-state mirroring baseline
    geo_failovers: jax.Array         # uint32 — region-kill re-homings
    hist_residue: obs_hist.Hist    # per-round unshipped-backlog rows
    hist_useful_bytes: obs_hist.Hist  # per-round post-mask payload bytes
    hist_ack_depth: obs_hist.Hist  # per-round ack-window depth
    hist_packed_bytes: obs_hist.Hist  # per-round post-packing wire bytes
    hist_dispatch_us: obs_hist.Hist   # host-timed dispatch wall-clock (µs)
    hist_ingest_batch: obs_hist.Hist  # per-flush coalesced-batch op count
    hist_push_bytes: obs_hist.Hist    # per-cohort δ push payload bytes
    hist_persist_us: obs_hist.Hist    # per-row background persist wall-clock (µs)
    # Trace-plane stage latencies (crdt_tpu/obs/trace.py — host-filled
    # per completed sampled trace via Tracer.annotate):
    hist_queue_wait_us: obs_hist.Hist    # submit → coalesce
    hist_dispatch_gap_us: obs_hist.Hist  # coalesce → dispatch
    hist_durable_lag_us: obs_hist.Hist   # dispatch → durable (WAL/persist)
    hist_push_lag_us: obs_hist.Hist      # dispatch → fan-out push
    hist_ack_lag_us: obs_hist.Hist       # push → client ack
    hist_freshness_us: obs_hist.Hist     # submit → client ack (end-to-end)
    hist_geo_watermark_lag: obs_hist.Hist  # per-read mirror watermark lag


def zeros() -> Telemetry:
    """The accumulation identity."""
    return Telemetry(
        merges=jnp.zeros((), jnp.uint32),
        slots_changed=jnp.zeros((), jnp.uint32),
        deferred_depth=jnp.zeros((), jnp.uint32),
        bytes_exchanged=jnp.zeros((), jnp.float32),
        bytes_useful=jnp.zeros((), jnp.float32),
        residue=jnp.zeros((), jnp.int32),
        widen_pressure=jnp.zeros((), jnp.float32),
        reclaimed_slots=jnp.zeros((), jnp.uint32),
        reclaimed_bytes=jnp.zeros((), jnp.float32),
        frontier_lag=jnp.zeros((), jnp.uint32),
        stream_blocks=jnp.zeros((), jnp.uint32),
        stream_staged_bytes=jnp.zeros((), jnp.float32),
        stream_overlap_hit=jnp.zeros((), jnp.uint32),
        faults_dropped=jnp.zeros((), jnp.uint32),
        faults_rejected=jnp.zeros((), jnp.uint32),
        faults_delayed=jnp.zeros((), jnp.uint32),
        bytes_acked_skipped=jnp.zeros((), jnp.float32),
        ack_window_depth=jnp.zeros((), jnp.uint32),
        wal_bytes=jnp.zeros((), jnp.float32),
        wal_fsyncs=jnp.zeros((), jnp.uint32),
        snapshots_written=jnp.zeros((), jnp.uint32),
        replayed_records=jnp.zeros((), jnp.uint32),
        torn_tail_truncated=jnp.zeros((), jnp.uint32),
        recovery_rounds=jnp.zeros((), jnp.uint32),
        live_ranks=jnp.zeros((), jnp.uint32),
        scaleout_admits=jnp.zeros((), jnp.uint32),
        scaleout_drains=jnp.zeros((), jnp.uint32),
        bootstrap_bytes=jnp.zeros((), jnp.float32),
        wire_packed_bytes=jnp.zeros((), jnp.float32),
        live_tenants=jnp.zeros((), jnp.uint32),
        evicted_tenants=jnp.zeros((), jnp.uint32),
        ingest_coalesced_ops=jnp.zeros((), jnp.uint32),
        subscribers_live=jnp.zeros((), jnp.uint32),
        cohorts_per_dispatch=jnp.zeros((), jnp.uint32),
        delta_push_bytes=jnp.zeros((), jnp.float32),
        resync_fallbacks=jnp.zeros((), jnp.uint32),
        serve_wal_bytes=jnp.zeros((), jnp.float32),
        serve_overlap_hit=jnp.zeros((), jnp.uint32),
        rebalance_moves=jnp.zeros((), jnp.uint32),
        regions_live=jnp.zeros((), jnp.uint32),
        geo_home_tenants=jnp.zeros((), jnp.uint32),
        geo_exchanges=jnp.zeros((), jnp.uint32),
        geo_exchange_bytes=jnp.zeros((), jnp.float32),
        geo_full_mirror_bytes=jnp.zeros((), jnp.float32),
        geo_failovers=jnp.zeros((), jnp.uint32),
        hist_residue=obs_hist.zeros(),
        hist_useful_bytes=obs_hist.zeros(),
        hist_ack_depth=obs_hist.zeros(),
        hist_packed_bytes=obs_hist.zeros(),
        hist_dispatch_us=obs_hist.zeros(),
        hist_ingest_batch=obs_hist.zeros(),
        hist_push_bytes=obs_hist.zeros(),
        hist_persist_us=obs_hist.zeros(),
        hist_queue_wait_us=obs_hist.zeros(),
        hist_dispatch_gap_us=obs_hist.zeros(),
        hist_durable_lag_us=obs_hist.zeros(),
        hist_push_lag_us=obs_hist.zeros(),
        hist_ack_lag_us=obs_hist.zeros(),
        hist_freshness_us=obs_hist.zeros(),
        hist_geo_watermark_lag=obs_hist.zeros(),
    )


def specs() -> Telemetry:
    """shard_map out_specs: every field is replicated — scalars and
    the ``hist_*`` counter planes alike (the Hist subtrees mirror
    their structure so no pytree-prefix resolution is needed)."""
    from jax.sharding import PartitionSpec as P

    return Telemetry(*(
        obs_hist.Hist(counts=P(), total=P())
        if obs_hist.is_hist_field(f) else P()
        for f in Telemetry._fields
    ))


def combine(a: Telemetry, b: Telemetry) -> Telemetry:
    """Fold two runs' telemetry (e.g. across elastic migrations):
    throughput counters add; the final-state gauges (depth, residue,
    pressure) come from the LATER run — they describe where the state
    ended, not a rate."""
    return Telemetry(
        merges=a.merges + b.merges,
        slots_changed=a.slots_changed + b.slots_changed,
        bytes_exchanged=a.bytes_exchanged + b.bytes_exchanged,
        bytes_useful=a.bytes_useful + b.bytes_useful,
        reclaimed_slots=a.reclaimed_slots + b.reclaimed_slots,
        reclaimed_bytes=a.reclaimed_bytes + b.reclaimed_bytes,
        stream_blocks=a.stream_blocks + b.stream_blocks,
        stream_staged_bytes=a.stream_staged_bytes + b.stream_staged_bytes,
        stream_overlap_hit=a.stream_overlap_hit + b.stream_overlap_hit,
        faults_dropped=a.faults_dropped + b.faults_dropped,
        faults_rejected=a.faults_rejected + b.faults_rejected,
        faults_delayed=a.faults_delayed + b.faults_delayed,
        bytes_acked_skipped=a.bytes_acked_skipped + b.bytes_acked_skipped,
        wal_bytes=a.wal_bytes + b.wal_bytes,
        wal_fsyncs=a.wal_fsyncs + b.wal_fsyncs,
        snapshots_written=a.snapshots_written + b.snapshots_written,
        replayed_records=a.replayed_records + b.replayed_records,
        torn_tail_truncated=a.torn_tail_truncated + b.torn_tail_truncated,
        recovery_rounds=a.recovery_rounds + b.recovery_rounds,
        scaleout_admits=a.scaleout_admits + b.scaleout_admits,
        scaleout_drains=a.scaleout_drains + b.scaleout_drains,
        bootstrap_bytes=a.bootstrap_bytes + b.bootstrap_bytes,
        wire_packed_bytes=a.wire_packed_bytes + b.wire_packed_bytes,
        ingest_coalesced_ops=(
            a.ingest_coalesced_ops + b.ingest_coalesced_ops
        ),
        cohorts_per_dispatch=(
            a.cohorts_per_dispatch + b.cohorts_per_dispatch
        ),
        delta_push_bytes=a.delta_push_bytes + b.delta_push_bytes,
        resync_fallbacks=a.resync_fallbacks + b.resync_fallbacks,
        serve_wal_bytes=a.serve_wal_bytes + b.serve_wal_bytes,
        serve_overlap_hit=a.serve_overlap_hit + b.serve_overlap_hit,
        rebalance_moves=a.rebalance_moves + b.rebalance_moves,
        geo_exchanges=a.geo_exchanges + b.geo_exchanges,
        geo_exchange_bytes=a.geo_exchange_bytes + b.geo_exchange_bytes,
        geo_full_mirror_bytes=(
            a.geo_full_mirror_bytes + b.geo_full_mirror_bytes
        ),
        geo_failovers=a.geo_failovers + b.geo_failovers,
        hist_residue=obs_hist.merge(a.hist_residue, b.hist_residue),
        hist_useful_bytes=obs_hist.merge(
            a.hist_useful_bytes, b.hist_useful_bytes
        ),
        hist_ack_depth=obs_hist.merge(a.hist_ack_depth, b.hist_ack_depth),
        hist_packed_bytes=obs_hist.merge(
            a.hist_packed_bytes, b.hist_packed_bytes
        ),
        hist_dispatch_us=obs_hist.merge(
            a.hist_dispatch_us, b.hist_dispatch_us
        ),
        hist_ingest_batch=obs_hist.merge(
            a.hist_ingest_batch, b.hist_ingest_batch
        ),
        hist_push_bytes=obs_hist.merge(
            a.hist_push_bytes, b.hist_push_bytes
        ),
        hist_persist_us=obs_hist.merge(
            a.hist_persist_us, b.hist_persist_us
        ),
        hist_queue_wait_us=obs_hist.merge(
            a.hist_queue_wait_us, b.hist_queue_wait_us
        ),
        hist_dispatch_gap_us=obs_hist.merge(
            a.hist_dispatch_gap_us, b.hist_dispatch_gap_us
        ),
        hist_durable_lag_us=obs_hist.merge(
            a.hist_durable_lag_us, b.hist_durable_lag_us
        ),
        hist_push_lag_us=obs_hist.merge(
            a.hist_push_lag_us, b.hist_push_lag_us
        ),
        hist_ack_lag_us=obs_hist.merge(
            a.hist_ack_lag_us, b.hist_ack_lag_us
        ),
        hist_freshness_us=obs_hist.merge(
            a.hist_freshness_us, b.hist_freshness_us
        ),
        hist_geo_watermark_lag=obs_hist.merge(
            a.hist_geo_watermark_lag, b.hist_geo_watermark_lag
        ),
        deferred_depth=b.deferred_depth,
        residue=b.residue,
        widen_pressure=b.widen_pressure,
        frontier_lag=b.frontier_lag,
        ack_window_depth=b.ack_window_depth,
        live_ranks=b.live_ranks,
        live_tenants=b.live_tenants,
        evicted_tenants=b.evicted_tenants,
        subscribers_live=b.subscribers_live,
        regions_live=b.regions_live,
        geo_home_tenants=b.geo_home_tenants,
    )


# ---- in-kernel reducers ---------------------------------------------------
# All pure lax/jnp on static shapes: safe inside jit AND shard_map.

def device_depth(state) -> jax.Array:
    """In-kernel ``deferred_depth``: max over leading (replica) lanes of
    valid parked slots summed across every ``*dvalid`` buffer level —
    the jit-transparent twin of ``utils.metrics.deferred_depth`` (same
    masked-epoch field convention, uint32 instead of the -1 host
    sentinel)."""
    total = None

    def walk(node):
        nonlocal total
        for name in node._fields:
            child = getattr(node, name)
            if name.endswith("dvalid"):
                d = jnp.sum(child.astype(jnp.uint32), axis=-1)
                total = d if total is None else total + d
            elif hasattr(child, "_fields"):
                walk(child)

    if hasattr(state, "_fields"):
        walk(state)
    if total is None:
        return jnp.zeros((), jnp.uint32)
    return jnp.max(total).astype(jnp.uint32)


def device_pressure(state) -> jax.Array:
    """Max occupancy fraction over the bounded parked buffers (every
    ``*dvalid`` level): 1.0 = some replica's buffer is at capacity —
    the widen-before-overflow signal, in-kernel."""
    worst = None

    def walk(node):
        nonlocal worst
        for name in node._fields:
            child = getattr(node, name)
            if name.endswith("dvalid"):
                cap = max(child.shape[-1], 1)
                frac = jnp.max(
                    jnp.sum(child.astype(jnp.float32), axis=-1) / cap
                )
                worst = frac if worst is None else jnp.maximum(worst, frac)
            elif hasattr(child, "_fields"):
                walk(child)

    if hasattr(state, "_fields"):
        walk(state)
    if worst is None:
        return jnp.zeros((), jnp.float32)
    return worst.astype(jnp.float32)


def generic_slots_changed(a, b) -> jax.Array:
    """Fallback slots-changed counter: entries that differ across EVERY
    state plane. Exact for element-replicated layouts; kinds with a
    sharded content plane use their ops kernel's specialized counter
    (``ops.orswot.changed_members`` etc.) so element-shard psums don't
    double count replicated planes."""
    total = jnp.zeros((), jnp.uint32)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        total = total + jnp.sum(x != y, dtype=jnp.uint32)
    return total


def shipped_bytes(pytree) -> int:
    """STATIC per-exchange byte count of a shipped pytree (shapes are
    static under tracing, so this is a Python int even in-kernel)."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(pytree))


def packet_useful_bytes(pkt) -> jax.Array:
    """DYNAMIC post-mask byte count of one δ packet (``bytes_useful``):
    slot lanes weighted by the packet's slot ``valid`` mask, parked
    buffers by their ``*dvalid`` masks. Walks the packet convention the
    δ flavors share — a leaf packet carries ``idx``/``valid`` plus its
    slot planes, wrapper packets nest the core packet first with one
    parked group (``[k|o]?d{cl,mask,keys,valid}``) riding whole per
    level — so every current and future ``nested_delta`` composition is
    covered without per-flavor byte tables. Pure lax on static shapes:
    safe inside jit and shard_map."""
    total = jnp.zeros((), jnp.float32)

    def group(mask, values):
        n = max(mask.shape[0], 1)
        per = sum(
            (leaf.size // n) * leaf.dtype.itemsize
            for v in values
            for leaf in jax.tree.leaves(v)
        )
        return jnp.sum(mask, dtype=jnp.float32) * per

    def walk(node):
        nonlocal total
        names = node._fields
        parked = {}
        for f in names:
            if f.endswith("dvalid"):
                pref = f[: -len("dvalid")]
                parked[pref] = [
                    getattr(node, pref + s)
                    for s in ("dcl", "dmask", "dkeys", "dvalid")
                    if pref + s in names
                ]
        parked_names = {
            pref + s
            for pref in parked
            for s in ("dcl", "dmask", "dkeys", "dvalid")
            if pref + s in names
        }
        if "idx" in names:  # leaf packet: slot planes gated by `valid`
            total = total + group(
                node.valid,
                [getattr(node, f) for f in names if f not in parked_names],
            )
        else:  # wrapper packet: the core packet rides first
            walk(node[0])
        for bufs in parked.values():
            total = total + group(bufs[-1], bufs)  # bufs[-1] is *dvalid

    walk(pkt)
    return total


# ---- host-side drain ------------------------------------------------------

def is_concrete(tel: Telemetry) -> bool:
    return not any(
        isinstance(x, jax.core.Tracer) for x in jax.tree.leaves(tel)
    )


def to_dict(tel: Telemetry) -> Dict[str, Any]:
    """Host ints/floats for a CONCRETE Telemetry (exporter/JSONL form)."""
    return {
        "merges": int(tel.merges),
        "slots_changed": int(tel.slots_changed),
        "deferred_depth": int(tel.deferred_depth),
        "bytes_exchanged": float(tel.bytes_exchanged),
        "bytes_useful": float(tel.bytes_useful),
        "residue": int(tel.residue),
        "widen_pressure": float(tel.widen_pressure),
        "reclaimed_slots": int(tel.reclaimed_slots),
        "reclaimed_bytes": float(tel.reclaimed_bytes),
        "frontier_lag": int(tel.frontier_lag),
        "stream_blocks": int(tel.stream_blocks),
        "stream_staged_bytes": float(tel.stream_staged_bytes),
        "stream_overlap_hit": int(tel.stream_overlap_hit),
        "faults_dropped": int(tel.faults_dropped),
        "faults_rejected": int(tel.faults_rejected),
        "faults_delayed": int(tel.faults_delayed),
        "bytes_acked_skipped": float(tel.bytes_acked_skipped),
        "ack_window_depth": int(tel.ack_window_depth),
        "wal_bytes": float(tel.wal_bytes),
        "wal_fsyncs": int(tel.wal_fsyncs),
        "snapshots_written": int(tel.snapshots_written),
        "replayed_records": int(tel.replayed_records),
        "torn_tail_truncated": int(tel.torn_tail_truncated),
        "recovery_rounds": int(tel.recovery_rounds),
        "live_ranks": int(tel.live_ranks),
        "scaleout_admits": int(tel.scaleout_admits),
        "scaleout_drains": int(tel.scaleout_drains),
        "bootstrap_bytes": float(tel.bootstrap_bytes),
        "wire_packed_bytes": float(tel.wire_packed_bytes),
        "live_tenants": int(tel.live_tenants),
        "evicted_tenants": int(tel.evicted_tenants),
        "ingest_coalesced_ops": int(tel.ingest_coalesced_ops),
        "subscribers_live": int(tel.subscribers_live),
        "cohorts_per_dispatch": int(tel.cohorts_per_dispatch),
        "delta_push_bytes": float(tel.delta_push_bytes),
        "resync_fallbacks": int(tel.resync_fallbacks),
        "serve_wal_bytes": float(tel.serve_wal_bytes),
        "serve_overlap_hit": int(tel.serve_overlap_hit),
        "rebalance_moves": int(tel.rebalance_moves),
        "regions_live": int(tel.regions_live),
        "geo_home_tenants": int(tel.geo_home_tenants),
        "geo_exchanges": int(tel.geo_exchanges),
        "geo_exchange_bytes": float(tel.geo_exchange_bytes),
        "geo_full_mirror_bytes": float(tel.geo_full_mirror_bytes),
        "geo_failovers": int(tel.geo_failovers),
        "hist_residue": obs_hist.to_dict(tel.hist_residue),
        "hist_useful_bytes": obs_hist.to_dict(tel.hist_useful_bytes),
        "hist_ack_depth": obs_hist.to_dict(tel.hist_ack_depth),
        "hist_packed_bytes": obs_hist.to_dict(tel.hist_packed_bytes),
        "hist_dispatch_us": obs_hist.to_dict(tel.hist_dispatch_us),
        "hist_ingest_batch": obs_hist.to_dict(tel.hist_ingest_batch),
        "hist_push_bytes": obs_hist.to_dict(tel.hist_push_bytes),
        "hist_persist_us": obs_hist.to_dict(tel.hist_persist_us),
        "hist_queue_wait_us": obs_hist.to_dict(tel.hist_queue_wait_us),
        "hist_dispatch_gap_us": obs_hist.to_dict(tel.hist_dispatch_gap_us),
        "hist_durable_lag_us": obs_hist.to_dict(tel.hist_durable_lag_us),
        "hist_push_lag_us": obs_hist.to_dict(tel.hist_push_lag_us),
        "hist_ack_lag_us": obs_hist.to_dict(tel.hist_ack_lag_us),
        "hist_freshness_us": obs_hist.to_dict(tel.hist_freshness_us),
        "hist_geo_watermark_lag": obs_hist.to_dict(
            tel.hist_geo_watermark_lag
        ),
    }


# Telemetry fields carrying a Hist subtree (self-describing serialized
# form; the exporter renders these as Prometheus histogram exposition,
# the schema validates them as the `histogram` kind).
HIST_FIELDS = tuple(
    f for f in Telemetry._fields if obs_hist.is_hist_field(f)
)


def time_dispatch(tel: Telemetry, seconds: float) -> Telemetry:
    """Fold one host-timed dispatch wall-clock into
    ``hist_dispatch_us`` (MICROSECONDS — log2 buckets resolve the
    µs..minutes range; a p99 over many dispatches is the ROADMAP
    serving-gate quantity). Host-side, concrete Telemetry only (the
    ``stream_*``/``wal_*`` fill discipline): under an outer jit the
    pytree is traced, host timing is meaningless, and the input is
    returned untouched."""
    if not is_concrete(tel):
        return tel
    return tel._replace(
        hist_dispatch_us=obs_hist.observe(
            tel.hist_dispatch_us, seconds * 1e6
        )
    )


def counter_increments(kind: str, d: Dict[str, Any]) -> Dict[str, int]:
    """The registry COUNTER increments one recorded Telemetry dict
    (:func:`to_dict`) produces — THE single source of truth shared by
    :func:`record` (which applies them) and ``tools/obs_report.py``
    (which re-folds a flight dump's ``telemetry`` events through this
    exact mapping and compares the result bit-exactly against the live
    registry — a drift here would break that audit, never fork the two
    sides). Gauge observations (depth/residue/pressure/lag and the
    histogram quantile summaries) are NOT counters and live in
    :func:`record` only."""
    inc = {
        f"telemetry.{kind}.merges": d["merges"],
        f"telemetry.{kind}.slots_changed": d["slots_changed"],
        f"telemetry.{kind}.bytes_exchanged": int(d["bytes_exchanged"]),
        f"telemetry.{kind}.bytes_useful": int(d["bytes_useful"]),
        f"telemetry.{kind}.reclaimed_slots": d["reclaimed_slots"],
        f"telemetry.{kind}.reclaimed_bytes": int(d["reclaimed_bytes"]),
        f"telemetry.{kind}.stream.blocks": d["stream_blocks"],
        f"telemetry.{kind}.stream.staged_bytes": int(
            d["stream_staged_bytes"]
        ),
        f"telemetry.{kind}.stream.overlap_hit": d["stream_overlap_hit"],
        f"telemetry.{kind}.faults.packets_dropped": d["faults_dropped"],
        f"telemetry.{kind}.faults.packets_rejected": d["faults_rejected"],
        f"telemetry.{kind}.faults.packets_delayed": d["faults_delayed"],
        f"telemetry.{kind}.bytes_acked_skipped": int(
            d["bytes_acked_skipped"]
        ),
        f"telemetry.{kind}.wal_bytes": int(d["wal_bytes"]),
        f"telemetry.{kind}.wal_fsyncs": d["wal_fsyncs"],
        f"telemetry.{kind}.snapshots_written": d["snapshots_written"],
        f"telemetry.{kind}.replayed_records": d["replayed_records"],
        f"telemetry.{kind}.torn_tail_truncated": d["torn_tail_truncated"],
        f"telemetry.{kind}.recovery_rounds": d["recovery_rounds"],
        f"telemetry.{kind}.scaleout.admits": d["scaleout_admits"],
        f"telemetry.{kind}.scaleout.drains": d["scaleout_drains"],
        f"telemetry.{kind}.scaleout.bootstrap_bytes": int(
            d["bootstrap_bytes"]
        ),
        f"telemetry.{kind}.wire.packed_bytes": int(
            d["wire_packed_bytes"]
        ),
        f"telemetry.{kind}.serve.ingest_coalesced_ops": d[
            "ingest_coalesced_ops"
        ],
        f"telemetry.{kind}.fanout.cohorts_per_dispatch": d[
            "cohorts_per_dispatch"
        ],
        f"telemetry.{kind}.fanout.delta_push_bytes": int(
            d["delta_push_bytes"]
        ),
        f"telemetry.{kind}.fanout.resync_fallbacks": d[
            "resync_fallbacks"
        ],
        f"telemetry.{kind}.serve.wal_bytes": int(d["serve_wal_bytes"]),
        f"telemetry.{kind}.serve.overlap_hit": d["serve_overlap_hit"],
        f"telemetry.{kind}.serve.rebalance_moves": d["rebalance_moves"],
        f"telemetry.{kind}.geo.exchanges": d["geo_exchanges"],
        f"telemetry.{kind}.geo.exchange_bytes": int(
            d["geo_exchange_bytes"]
        ),
        f"telemetry.{kind}.geo.full_mirror_bytes": int(
            d["geo_full_mirror_bytes"]
        ),
        f"telemetry.{kind}.geo.failovers": d["geo_failovers"],
    }
    # Histogram per-bucket counters fold bit-exactly across runs —
    # exactly what tools/obs_report.py cross-checks a dump against.
    for field in HIST_FIELDS:
        hd = d[field]
        n = sum(hd["counts"])
        if not n:
            continue
        base = f"telemetry.{kind}.hist.{field[len('hist_'):]}"
        inc[f"{base}.count"] = n
        for i, c in enumerate(hd["counts"]):
            if c:
                inc[f"{base}.bucket{i:02d}"] = c
    return inc


def record(kind: str, tel: Telemetry) -> None:
    """Drain a concrete Telemetry into the host registry under
    ``telemetry.<kind>.*`` (counters for the monotone fields — the
    :func:`counter_increments` mapping — gauges for the final-state
    ones and the histogram p50/p95/p99 summaries). A no-op under
    tracing — the caller then owns the returned pytree (that is the
    whole point of it). With a flight recorder installed
    (crdt_tpu/obs/), each call additionally advances the correlation
    key's round coordinate and records one ``telemetry`` event
    carrying the full dict — the per-round timeline entry
    ``tools/obs_report.py`` re-folds."""
    if not is_concrete(tel):
        return
    d = to_dict(tel)
    for name, n in counter_increments(kind, d).items():
        metrics.count(name, n)
    metrics.observe(
        f"telemetry.{kind}.ack_window_depth", d["ack_window_depth"]
    )
    metrics.observe(f"telemetry.{kind}.live_ranks", d["live_ranks"])
    metrics.observe(f"telemetry.{kind}.live_tenants", d["live_tenants"])
    metrics.observe(
        f"telemetry.{kind}.evicted_tenants", d["evicted_tenants"]
    )
    metrics.observe(
        f"telemetry.{kind}.subscribers_live", d["subscribers_live"]
    )
    metrics.observe(f"telemetry.{kind}.regions_live", d["regions_live"])
    metrics.observe(
        f"telemetry.{kind}.geo_home_tenants", d["geo_home_tenants"]
    )
    metrics.observe(f"telemetry.{kind}.deferred_depth", d["deferred_depth"])
    metrics.observe(f"telemetry.{kind}.residue", d["residue"])
    metrics.observe(f"telemetry.{kind}.widen_pressure", d["widen_pressure"])
    metrics.observe(f"telemetry.{kind}.frontier_lag", d["frontier_lag"])
    for field in HIST_FIELDS:
        hd = d[field]
        if not sum(hd["counts"]):
            continue
        base = f"telemetry.{kind}.hist.{field[len('hist_'):]}"
        s = obs_hist.summary(hd)
        for q in ("p50", "p95", "p99"):
            metrics.observe(f"{base}.{q}", s[q])
    from .obs import recorder as _rec

    if _rec.get_recorder() is not None:
        # Emit FIRST, advance AFTER: the telemetry drain is the last
        # event of its dispatch, so everything the dispatch emitted
        # earlier (WAL fsyncs, fault counters) shares its round
        # coordinate — advancing first would split one dispatch across
        # two rounds on the postmortem timeline.
        _rec.emit("telemetry", kind=kind, **d)
        _rec.advance_round()


# ---- span tracing ---------------------------------------------------------

_trace_lock = threading.Lock()
_trace_events: list = []
_trace_path: Optional[str] = None
_MAX_BUFFERED_EVENTS = 65536
_local = threading.local()


def configure_tracing(path: Optional[str]) -> None:
    """Point span JSONL emission at ``path`` (append mode; None = keep
    events only in the in-memory ring for :func:`drain_events`)."""
    global _trace_path
    with _trace_lock:
        _trace_path = path


def drain_events() -> list:
    """Pop and return every buffered span event (oldest first)."""
    with _trace_lock:
        out, _trace_events[:] = list(_trace_events), []
    return out


def _emit(event: Dict[str, Any]) -> None:
    # Stamp the flight recorder's (generation, round, rank) correlation
    # key when one is installed, so spans and flight events line up on
    # one timeline (obs/recorder.py module docstring).
    from .obs import recorder as _rec

    k = _rec.current_key()
    if k is not None:
        event.setdefault("gen", k[0])
        event.setdefault("round", k[1])
        event.setdefault("rank", k[2])
    with _trace_lock:
        _trace_events.append(event)
        del _trace_events[:-_MAX_BUFFERED_EVENTS]
        path = _trace_path
    if path:
        try:
            # default=str: attrs may carry numpy/jnp scalars; tracing
            # must never take down the traced program.
            line = json.dumps(event, default=str)
            with open(path, "a") as f:
                f.write(line + "\n")
        except (OSError, TypeError, ValueError):
            pass


@contextlib.contextmanager
def span(name: str, **attrs):
    """A named span: structured JSONL event on exit (wall-clock start,
    duration, attrs, parent span) AND the same name nested into
    ``jax.named_scope`` + ``jax.profiler.TraceAnnotation``, so host
    spans line up with XProf device timelines. Also feeds the registry
    timer histogram (``<name>_seconds`` gauge) so snapshot-only
    consumers see span durations too. Attrs must be JSON-serializable.

    When a flight recorder is installed (``crdt_tpu.obs.install`` —
    obs/recorder.py), every span event additionally carries the
    recorder's monotonic ``(generation, round, rank)`` correlation key
    as ``gen``/``round``/``rank`` fields, so spans interleave with the
    recorder's per-round subsystem events (fault draws, membership
    decisions, WAL watermarks, scale-out votes) on ONE timeline in a
    ``FlightRecorder.dump()`` postmortem artifact and in
    ``tools/obs_report.py``'s rendering of it.
    """
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    parent = stack[-1] if stack else None
    stack.append(name)
    t_wall = time.time()
    t0 = time.perf_counter()
    try:
        with contextlib.ExitStack() as es:
            # The registry timer owns the `<name>_seconds` gauge (same
            # shape as every other metrics.time site); the local clock
            # below only feeds the trace event.
            es.enter_context(metrics.time(name))
            es.enter_context(jax.named_scope(name))
            try:
                es.enter_context(jax.profiler.TraceAnnotation(name))
            except Exception:
                pass  # profiler backend unavailable — host event still fires
            yield
    finally:
        stack.pop()
        dur = time.perf_counter() - t0
        _emit({
            "record": "span",
            "name": name,
            "ts": t_wall,
            "dur_s": dur,
            "parent": parent,
            "attrs": attrs,
        })


def reset_residue_warnings() -> None:
    """Re-arm the δ-ring's once-per-kind residue warning (the dedupe
    lives in parallel.delta_ring; re-exported here because tests and
    operators reach for it next to the telemetry registry — see
    tests/test_residue_warnings.py)."""
    from .parallel.delta_ring import reset_residue_warnings as _reset

    _reset()


__all__ = [
    "HIST_FIELDS", "Telemetry", "combine", "configure_tracing",
    "counter_increments",
    "device_depth", "device_pressure", "drain_events",
    "generic_slots_changed", "is_concrete", "packet_useful_bytes",
    "record", "reset_residue_warnings", "shipped_bytes",
    "span", "specs", "time_dispatch", "to_dict", "zeros",
]
