"""BatchedVClock — N replica clocks as one device array.

Oracle: ``crdt_tpu.vclock.VClock`` (reference: src/vclock.rs). The batch
is ``clocks[R, A]``; every lattice operation is a ``crdt_tpu.ops.vclock``
kernel.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..ops import vclock as ops
from ..utils import Interner, clock_lanes, transactional_apply
from ..vclock import VClock
from ..dot import Dot


class BatchedVClock:
    def __init__(self, n_replicas: int, actors: Optional[Interner] = None, n_actors: Optional[int] = None):
        self.actors = actors if actors is not None else Interner()
        n = n_actors if n_actors is not None else max(len(self.actors), 1)
        self.clocks = ops.zeros(n, batch=(n_replicas,))

    @property
    def n_replicas(self) -> int:
        return self.clocks.shape[0]

    @property
    def n_actors(self) -> int:
        return self.clocks.shape[-1]

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[VClock],
        actors: Optional[Interner] = None,
        n_actors: int = 0,
    ) -> "BatchedVClock":
        """``n_actors`` sets a capacity FLOOR above the actors present
        in ``pures`` — spare lanes later ops intern into."""
        actors = actors if actors is not None else Interner()
        for p in pures:
            for actor in p.dots:
                actors.intern(actor)
        n = max(len(actors), n_actors, 1)
        out = cls(len(pures), actors=actors, n_actors=n)
        mat = np.zeros(
            (len(pures), n),
            dtype=np.dtype(str(out.clocks.dtype)),
        )
        for i, p in enumerate(pures):
            for actor, counter in p.dots.items():
                mat[i, actors.id_of(actor)] = counter
        out.clocks = jnp.asarray(mat)
        return out

    def to_pure(self, i: int) -> VClock:
        row = np.asarray(self.clocks[i])
        return VClock(
            {self.actors[a]: int(c) for a, c in enumerate(row) if c > 0}
        )

    # ---- ops ----------------------------------------------------------
    def bounded_id(self, actor) -> int:
        """Actor id, guaranteed inside the lane universe (JAX scatter
        silently drops out-of-bounds indices — never rely on it). A
        never-seen actor is interned into a free lane if one exists."""
        return self.actors.bounded_intern(actor, self.n_actors, "actor")

    @transactional_apply("actors")
    def apply(self, replica: int, dot: Dot) -> None:
        from .validation import strict_validate_dot

        strict_validate_dot(self.clocks[replica], self.actors, dot.actor, dot.counter)
        aid = self.bounded_id(dot.actor)
        self.clocks = self.clocks.at[replica].set(
            ops.apply_dot(self.clocks[replica], jnp.asarray(aid), jnp.asarray(dot.counter))
        )

    @transactional_apply("actors")
    def inc(self, replica: int, actor) -> None:
        aid = self.bounded_id(actor)
        self.clocks = self.clocks.at[replica].set(
            ops.inc(self.clocks[replica], jnp.asarray(aid))
        )

    @transactional_apply("actors")
    def reset_remove(self, replica: int, clock) -> None:
        """``Causal::reset_remove`` on one replica: forget lanes the
        given ``VClock`` dominates (reference: src/vclock.rs
        ResetRemove/forget; oracle: crdt_tpu/vclock.py)."""
        cl = clock_lanes(clock, self.actors, self.n_actors,
                         dtype=np.dtype(str(self.clocks.dtype)))
        self.clocks = self.clocks.at[replica].set(
            ops.reset_remove(self.clocks[replica], jnp.asarray(cl))
        )

    def merge_from(self, dst: int, src: int) -> None:
        self.clocks = self.clocks.at[dst].set(
            ops.merge(self.clocks[dst], self.clocks[src])
        )

    def fold(self) -> VClock:
        """Join all replicas (full-mesh anti-entropy in one reduction)."""
        joined = ops.fold(self.clocks)
        row = np.asarray(joined)
        return VClock({self.actors[a]: int(c) for a, c in enumerate(row) if c > 0})

    def compare(self, i: int, j: int) -> Optional[int]:
        code = int(ops.compare(self.clocks[i], self.clocks[j]))
        return None if code == ops.CONCURRENT else code
