"""Batched G/PN counters — thin wrappers over the clock kernels.

Oracle: ``crdt_tpu.pure.gcounter`` / ``pncounter`` (reference:
src/gcounter.rs, src/pncounter.rs). A G-Counter IS a clock, so the
batched form delegates storage and conversion to ``BatchedVClock`` —
``counters[R, A]`` — and a fold + exact host-side lane sum reads the
converged total (BASELINE config 1). PN composes two clock batches.

Reads are exact Python ints (the reference's BigInt read, SURVEY.md
§7.3): lane sums happen host-side because device accumulators are u32
under JAX's default x64-disabled mode.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..ops import vclock as ops
from ..pure.gcounter import GCounter
from ..pure.pncounter import PNCounter
from ..utils import Interner
from .vclock import BatchedVClock


def _exact_sum(row) -> int:
    return sum(int(c) for c in np.asarray(row))


def _check_steps(steps: int, dtype) -> None:
    limit = int(np.iinfo(np.dtype(str(dtype))).max)
    if not 0 <= steps <= limit:
        raise ValueError(
            f"steps must fit the counter dtype (0 <= steps <= {limit}), got {steps}"
        )


def _bump(batch: "BatchedVClock", replica: int, actor, steps: int) -> None:
    """The one counter-increment sequence (GCounter.inc, PNCounter.inc/
    dec are the same op on different clock batches): bounds-check steps
    against the lane dtype, allocate the actor lane, trap saturation in
    strict mode (the only path that pays the device read), and add."""
    from ..config import config

    dt = batch.clocks.dtype
    _check_steps(steps, dt)
    aid = batch.bounded_id(actor)
    if config.strict:
        from .validation import strict_check_headroom

        strict_check_headroom(batch.clocks[replica, aid], actor, steps, dt)
    batch.clocks = batch.clocks.at[replica, aid].add(dt.type(steps))


class BatchedGCounter:
    def __init__(self, n_replicas: int, actors: Optional[Interner] = None, n_actors: Optional[int] = None):
        self.inner = BatchedVClock(n_replicas, actors=actors, n_actors=n_actors)

    @property
    def actors(self) -> Interner:
        return self.inner.actors

    @property
    def n_replicas(self) -> int:
        return self.inner.clocks.shape[0]

    @classmethod
    def from_pure(cls, pures: Sequence[GCounter], actors: Optional[Interner] = None) -> "BatchedGCounter":
        out = cls(0)
        out.inner = BatchedVClock.from_pure([p.inner for p in pures], actors=actors)
        return out

    def to_pure(self, i: int) -> GCounter:
        return GCounter(self.inner.to_pure(i))

    def inc(self, replica: int, actor, steps: int = 1) -> None:
        _bump(self.inner, replica, actor, steps)

    def fold_read(self) -> int:
        """Converged total: one join + one lane sum (config 1's kernel)."""
        return _exact_sum(ops.fold(self.inner.clocks))

    def read(self, i: int) -> int:
        return _exact_sum(self.inner.clocks[i])


class BatchedPNCounter:
    def __init__(self, n_replicas: int, actors: Optional[Interner] = None, n_actors: Optional[int] = None):
        actors = actors if actors is not None else Interner()
        self.p = BatchedVClock(n_replicas, actors=actors, n_actors=n_actors)
        self.n = BatchedVClock(n_replicas, actors=actors, n_actors=n_actors)

    @property
    def actors(self) -> Interner:
        return self.p.actors

    @property
    def n_replicas(self) -> int:
        return self.p.clocks.shape[0]

    @classmethod
    def from_pure(cls, pures: Sequence[PNCounter], actors: Optional[Interner] = None) -> "BatchedPNCounter":
        actors = actors if actors is not None else Interner()
        for pure in pures:
            for actor in (*pure.p.inner.dots, *pure.n.inner.dots):
                actors.intern(actor)
        out = cls(0)
        out.p = BatchedVClock.from_pure([x.p.inner for x in pures], actors=actors)
        out.n = BatchedVClock.from_pure([x.n.inner for x in pures], actors=actors)
        return out

    def to_pure(self, i: int) -> PNCounter:
        return PNCounter(GCounter(self.p.to_pure(i)), GCounter(self.n.to_pure(i)))

    def inc(self, replica: int, actor, steps: int = 1) -> None:
        _bump(self.p, replica, actor, steps)

    def dec(self, replica: int, actor, steps: int = 1) -> None:
        _bump(self.n, replica, actor, steps)

    def fold_read(self) -> int:
        """Converged p − n (exact Python int at the API edge, preserving
        the reference's BigInt read — SURVEY.md §7.3)."""
        return _exact_sum(ops.fold(self.p.clocks)) - _exact_sum(ops.fold(self.n.clocks))

    def read(self, i: int) -> int:
        """One replica's local p − n (reference: src/pncounter.rs
        ``read``), exact host int."""
        return _exact_sum(self.p.clocks[i]) - _exact_sum(self.n.clocks[i])
