"""BatchedOrswot — N dense ORSWOT replicas on device.

Oracle: ``crdt_tpu.pure.orswot.Orswot`` (reference: src/orswot.rs). The
replica batch is an ``ops.orswot.OrswotState`` with leading axis R over a
fixed interned member universe E and actor universe A (dense mode,
SURVEY.md §7.1). Conversion to/from the oracle is lossless — including
the deferred-removal buffer — which is what the bit-identical A/B gate in
tests/test_models_orswot.py exercises.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import orswot as ops
from ..pure.orswot import Add, Orswot, Rm
from ..utils import Interner, clock_lanes, transactional, transactional_apply
from ..utils.metrics import metrics
from .validation import strict_validate_dot
from ..vclock import VClock


class DeferredOverflow(RuntimeError):
    """A parked remove could not be held: the deferred buffer exceeded its
    static capacity. Raise rather than silently dropping removal history —
    rebuild the model with a larger ``deferred_cap``."""


class BatchedOrswot:
    def __init__(
        self,
        n_replicas: int,
        n_members: int,
        n_actors: int,
        deferred_cap: int = 8,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
    ):
        self.members = members if members is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.state = ops.empty(n_members, n_actors, deferred_cap, batch=(n_replicas,))

    @property
    def n_replicas(self) -> int:
        return self.state.top.shape[0]

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Orswot],
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        deferred_cap: int = 8,
        n_members: int = 0,
        n_actors: int = 0,
    ) -> "BatchedOrswot":
        """``n_members`` / ``n_actors`` set capacity FLOORS above the
        names present in ``pures`` — spare lanes that later ops minting
        new members/actors intern into (``apply``)."""
        members = members if members is not None else Interner()
        actors = actors if actors is not None else Interner()
        for p in pures:
            for actor in p.clock.dots:
                actors.intern(actor)
            for m, entry in p.entries.items():
                members.intern(m)
                for actor in entry.dots:
                    actors.intern(actor)
            for clock, ms in p.deferred.items():
                for actor in clock.dots:
                    actors.intern(actor)
                for m in ms:
                    members.intern(m)

        r = len(pures)
        e = max(len(members), n_members, 1)
        a = max(len(actors), n_actors, 1)
        top = np.zeros((r, a), np.uint32)
        ctr = np.zeros((r, e, a), np.uint32)
        dcl = np.zeros((r, deferred_cap, a), np.uint32)
        dmask = np.zeros((r, deferred_cap, e), bool)
        dvalid = np.zeros((r, deferred_cap), bool)
        for i, p in enumerate(pures):
            for actor, c in p.clock.dots.items():
                top[i, actors.id_of(actor)] = c
            for m, entry in p.entries.items():
                for actor, c in entry.dots.items():
                    ctr[i, members.id_of(m), actors.id_of(actor)] = c
            if len(p.deferred) > deferred_cap:
                raise ValueError(
                    f"replica {i} has {len(p.deferred)} deferred removes; "
                    f"capacity is {deferred_cap}"
                )
            for d, (clock, ms) in enumerate(p.deferred.items()):
                for actor, c in clock.dots.items():
                    dcl[i, d, actors.id_of(actor)] = c
                for m in ms:
                    dmask[i, d, members.id_of(m)] = True
                dvalid[i, d] = True

        out = cls(r, e, a, deferred_cap, members=members, actors=actors)
        out.state = ops.OrswotState(
            top=jnp.asarray(top),
            ctr=jnp.asarray(ctr),
            dcl=jnp.asarray(dcl),
            dmask=jnp.asarray(dmask),
            dvalid=jnp.asarray(dvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Orswot:
        st = jax.device_get(self._row(self.state, i))
        out = Orswot()
        out.clock = VClock(
            {self.actors[a]: int(c) for a, c in enumerate(st.top) if c > 0}
        )
        present = st.ctr.any(axis=-1)
        for e in np.nonzero(present)[0]:
            out.entries[self.members[int(e)]] = VClock(
                {
                    self.actors[a]: int(c)
                    for a, c in enumerate(st.ctr[e])
                    if c > 0
                }
            )
        for d in np.nonzero(st.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.dcl[d]) if c > 0}
            )
            # Empty member sets are kept: the oracle's _defer_remove
            # stores deferred[clock] = set() too, and losslessness of
            # to_pure(from_pure(p)) is the A/B-gate contract.
            out.deferred[clock] = {
                self.members[int(e)] for e in np.nonzero(st.dmask[d])[0]
            }
        return out

    # ---- op path (CmRDT) ----------------------------------------------
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/orswot.rs ``CmRDT::apply``)."""
        # Unseen names intern into spare lanes (the reference's apply
        # accepts ops minting new members/actors — src/orswot.rs
        # CmRDT::apply inserts into its BTreeMaps); a full universe is a
        # clear IndexError, same convention as every other model. A
        # rejected op must be side-effect free (the validation.py
        # contract), so interner allocations roll back on any rejection.
        with transactional(self.members, self.actors):
            self._apply(replica, op)

    def _apply(self, replica: int, op) -> None:
        row = self._row(self.state, replica)
        na = self.state.top.shape[-1]
        ne = self.state.ctr.shape[-2]
        if isinstance(op, Add):
            strict_validate_dot(row.top, self.actors, op.dot.actor, op.dot.counter)
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            mask = np.zeros((ne,), bool)
            for m in op.members:
                mask[self.members.bounded_intern(m, ne, "member")] = True
            row = ops.apply_add(
                row, jnp.asarray(aid), jnp.asarray(op.dot.counter), jnp.asarray(mask)
            )
        elif isinstance(op, Rm):
            cl = clock_lanes(
                op.clock, self.actors, na, dtype=self.state.top.dtype
            )
            mask = np.zeros((ne,), bool)
            for m in op.members:
                mask[self.members.bounded_intern(m, ne, "member")] = True
            row, overflow = ops.apply_rm(row, jnp.asarray(cl), jnp.asarray(mask))
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: deferred buffer full "
                    f"(cap {self.state.dvalid.shape[-1]})"
                )
        else:
            raise TypeError(f"not an Orswot op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    @transactional_apply("actors")
    def reset_remove(self, replica: int, clock) -> None:
        """``Causal::reset_remove`` on one replica: forget all causal
        history the given ``VClock`` dominates (reference: src/orswot.rs
        ResetRemove impl; oracle: pure/orswot.py ``reset_remove``)."""
        cl = clock_lanes(
            clock, self.actors, self.state.top.shape[-1],
            dtype=self.state.top.dtype,
        )
        row = ops.reset_remove(self._row(self.state, replica), jnp.asarray(cl))
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    # ---- state path (CvRDT — the benchmark path) ----------------------
    def merge_from(self, dst: int, src: int) -> None:
        # No span here: this is the per-pair hot path, and a span per
        # merge floods the trace ring — the fold/mesh entry points are
        # the span granularity (telemetry.py).
        metrics.count("orswot.merges")
        joined, overflow = ops.join(
            self._row(self.state, dst), self._row(self.state, src)
        )
        if bool(overflow):
            raise DeferredOverflow(
                f"merge {src}->{dst}: deferred buffer full "
                f"(cap {self.state.dvalid.shape[-1]})"
            )
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, joined
        )

    def fold(self) -> Orswot:
        """Full-mesh anti-entropy: join all R replicas into the converged
        oracle-form state — via the fused one-HBM-pass Pallas fold on TPU
        backends, the jnp log2 reduction tree elsewhere (bit-identical
        either way; ops/pallas_kernels.py ``fold_auto``)."""
        from ..ops.pallas_kernels import fold_auto
        from ..telemetry import span

        metrics.count("orswot.merges", max(self.n_replicas - 1, 0))
        metrics.observe(
            "orswot.deferred_depth",
            float(jnp.sum(self.state.dvalid)) / max(self.n_replicas, 1),
        )
        with span("model.orswot.fold", replicas=self.n_replicas):
            folded, overflow = fold_auto(self.state)
        if bool(overflow):
            raise DeferredOverflow(
                f"fold: deferred buffer full (cap {self.state.dvalid.shape[-1]})"
            )
        tmp = BatchedOrswot(
            1,
            self.state.ctr.shape[-2],
            self.state.ctr.shape[-1],
            self.state.dcl.shape[-2],
            members=self.members,
            actors=self.actors,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)

    def members_of(self, i: int) -> frozenset:
        present = np.asarray(self.state.ctr[i].any(axis=-1))
        return frozenset(self.members[int(e)] for e in np.nonzero(present)[0])

    # ---- elastic capacity migration (elastic.py) ----------------------
    def widen_capacity(
        self,
        n_members: int = 0,
        n_actors: int = 0,
        deferred_cap: int = 0,
    ) -> None:
        """Re-encode the live device state into a wider layout in place
        — the sanctioned recovery from ``DeferredOverflow`` / a full
        interned universe (elastic.py drives this; the migration itself
        is ``ops.orswot.widen``). 0 keeps a width. Interners are
        untouched: ids keep their lanes, the new tail lanes are spare
        capacity, and the result is bit-identical to a from-scratch
        model built at the wider capacity holding the same state."""
        self.state = ops.widen(self.state, n_members, n_actors, deferred_cap)

    def narrow_capacity(
        self,
        n_members: int = 0,
        n_actors: int = 0,
        deferred_cap: int = 0,
    ) -> None:
        """The inverse migration — re-encode into a NARROWER layout in
        place (elastic.shrink drives this under the hysteresis policy).
        Refuses when a dropped lane holds live state OR a lane id the
        interner has minted (a member/actor name must keep its lane —
        ``ops.orswot.narrow`` checks the device planes, this checks the
        host tables). 0 keeps a width."""
        if n_members and n_members < len(self.members):
            raise ValueError(
                f"narrow refused: {len(self.members)} members interned > "
                f"target n_members {n_members}"
            )
        if n_actors and n_actors < len(self.actors):
            raise ValueError(
                f"narrow refused: {len(self.actors)} actors interned > "
                f"target n_actors {n_actors}"
            )
        self.state = ops.narrow(self.state, n_members, n_actors, deferred_cap)
