"""BatchedGSet — N G-Set replicas as a device membership bitmask.

Oracle: ``crdt_tpu.pure.gset.GSet`` (reference: src/gset.rs). The replica
batch is ``present[R, E]`` over a fixed interned member universe; merge is
logical OR and full-mesh anti-entropy is one ``any`` reduction.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..ops import gset as ops
from ..pure.gset import GSet
from ..utils import Interner


class BatchedGSet:
    def __init__(self, n_replicas: int, n_members: int, members: Optional[Interner] = None):
        self.members = members if members is not None else Interner()
        self.present = ops.zeros(n_members, batch=(n_replicas,))

    @property
    def n_replicas(self) -> int:
        return self.present.shape[0]

    @classmethod
    def from_pure(
        cls,
        pures: Sequence[GSet],
        members: Optional[Interner] = None,
        n_members: int = 0,
    ) -> "BatchedGSet":
        """``n_members`` sets a capacity FLOOR above the members present
        in ``pures`` — spare lanes later inserts intern into."""
        members = members if members is not None else Interner()
        for p in pures:
            for m in sorted(p.value, key=repr):
                members.intern(m)
        arr = np.zeros((len(pures), max(len(members), n_members, 1)), bool)
        for i, p in enumerate(pures):
            for m in p.value:
                arr[i, members.id_of(m)] = True
        out = cls(len(pures), arr.shape[1], members=members)
        out.present = jnp.asarray(arr)
        return out

    def to_pure(self, i: int) -> GSet:
        row = np.asarray(self.present[i])
        return GSet(self.members[int(e)] for e in np.nonzero(row)[0])

    def insert(self, replica: int, member) -> None:
        # bounded_intern raises BEFORE allocating when the universe is
        # full — a rejected insert is side-effect free (validation.py
        # contract), so contains() can never see a laneless name.
        mid = self.members.bounded_intern(
            member, self.present.shape[-1], "member"
        )
        self.present = self.present.at[replica, mid].set(True)

    def contains(self, replica: int, member) -> bool:
        if member not in self.members:
            return False
        mid = self.members.id_of(member)
        if mid >= self.present.shape[-1]:
            # Shared-interner name beyond this model's lanes (JAX gather
            # would clamp to the last lane and answer for a DIFFERENT
            # member).
            return False
        return bool(self.present[replica, mid])

    def merge_from(self, dst: int, src: int) -> None:
        self.present = self.present.at[dst].set(
            ops.join(self.present[dst], self.present[src])
        )

    def fold(self) -> GSet:
        row = np.asarray(ops.fold(self.present))
        return GSet(self.members[int(e)] for e in np.nonzero(row)[0])
