"""BatchedSparseNestedMap — N segment-encoded ``Map<K1, Map<K2, MVReg>>``
replicas.

The sparse sibling of ``BatchedNestedMap`` (models/map_nested.py): same
oracle (nested ``crdt_tpu.pure.map.Map`` with MVReg grandchildren,
reference src/map.rs ``V: Val<A>`` composition), same op surface, same
lossless ``to_pure``/``from_pure`` A/B boundary — but state proportional
to LIVE cells: the causal-composition invariant flattens the nest onto
ONE register-map cell table over the product key space (flat kid =
k1·span + k2, ``ops/sparse_mvmap.SparseMVMapLeaf``) wrapped by one
outer parked-keylist buffer (``ops/sparse_nest.SparseNestLevel``). Both
key universes are virtual, so K1·K2 can reach 2^31/A while a replica
holds kilobytes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dot import Dot
from ..ops import sparse_mvmap as smv
from ..pure.map import Map, MapRm, Nop, Up
from ..pure.mvreg import MVReg, Put
from ..utils import Interner, clock_lanes, pad_id_list, transactional_apply
from ..utils.metrics import metrics, observe_depth
from ..vclock import VClock
from .orswot import DeferredOverflow
from .registers import SlotOverflow
from .sparse_orswot import DotCapacityOverflow
from .validation import strict_validate_dot


class BatchedSparseNestedMap:
    def __init__(
        self,
        n_replicas: int,
        span: int,
        cell_cap: int = 64,
        n_actors: int = 16,
        sibling_cap: int = 4,
        deferred_cap: int = 4,
        rm_width: int = 8,
        key_deferred_cap: int = 4,
        key_rm_width: int = 8,
        n_keys1: int = 0,
        keys1: Optional[Interner] = None,
        keys2: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
    ):
        # The int32 packed cell key is (k1·span + k2)·A + act, so the
        # OUTER key universe must be bounded too: an unbounded k1 wraps
        # the key and joins silently lose cells. ``n_keys1`` defaults to
        # the widest universe the packing allows.
        cap1 = (2**31 - 1) // max(span * n_actors, 1)
        if cap1 < 1:
            raise ValueError("span * n_actors must fit the int32 packed key")
        if n_keys1 > cap1:
            # Mirror BatchedSparseMap's constructor check: a clamped
            # bound would silently weaken bounded_intern validation and
            # let later interns wrap the packed key.
            raise ValueError(
                f"n_keys1 = {n_keys1:,} exceeds the int32 packed-key cap "
                f"{cap1:,} at span {span} x {n_actors} actors "
                f"(shrink n_keys1, span, or n_actors)"
            )
        self.n_keys1 = n_keys1 if n_keys1 else cap1
        self.keys1 = keys1 if keys1 is not None else Interner()
        self.keys2 = keys2 if keys2 is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.values = values if values is not None else Interner()
        self.sibling_cap = sibling_cap
        self.level, self.state = smv.empty_map_mvreg(
            span, cell_cap, n_actors, deferred_cap, rm_width,
            key_deferred_cap, key_rm_width, sibling_cap, batch=(n_replicas,),
        )

    @property
    def n_replicas(self) -> int:
        return self.state.core.top.shape[0]

    @property
    def span(self) -> int:
        return self.level.span

    @property
    def cell_cap(self) -> int:
        return self.state.core.kid.shape[-1]

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Map],
        span: int = 1 << 16,
        cell_cap: int = 64,
        sibling_cap: int = 4,
        deferred_cap: int = 4,
        rm_width: int = 8,
        key_deferred_cap: int = 4,
        key_rm_width: int = 8,
        keys1: Optional[Interner] = None,
        keys2: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
        n_actors: int = 0,
    ) -> "BatchedSparseNestedMap":
        """Build segments straight from the oracle dicts — cost is
        O(live cells), independent of both key universes. ``span`` is
        the (virtual) inner-key universe width."""
        keys1 = keys1 if keys1 is not None else Interner()
        keys2 = keys2 if keys2 is not None else Interner()
        actors = actors if actors is not None else Interner()
        values = values if values is not None else Interner()
        for p in pures:
            for actor in p.clock.dots:
                actors.intern(actor)
            for k1, child in p.entries.items():
                keys1.intern(k1)
                if not isinstance(child, Map):
                    raise TypeError(
                        f"children must be Map, got {type(child)}"
                    )
                if child.clock != p.clock:
                    raise ValueError(
                        f"child at {k1!r} violates the covered invariant"
                    )
                for k2, reg in child.entries.items():
                    keys2.intern(k2)
                    if not isinstance(reg, MVReg):
                        raise TypeError(
                            f"inner children must be MVReg, got {type(reg)}"
                        )
                    for d, (clock, v) in reg.vals.items():
                        actors.intern(d.actor)
                        for actor in clock.dots:
                            actors.intern(actor)
                        values.intern(v)
                for clock, k2s in child.deferred.items():
                    for actor in clock.dots:
                        actors.intern(actor)
                    for k2 in k2s:
                        keys2.intern(k2)
            for clock, k1s in p.deferred.items():
                for actor in clock.dots:
                    actors.intern(actor)
                for k1 in k1s:
                    keys1.intern(k1)
        if len(keys2) > span:
            raise ValueError(
                f"{len(keys2)} inner keys exceed the span {span}"
            )
        na_bound = max(len(actors), n_actors, 1)
        if len(keys1) * span * na_bound > 2**31 - 1:
            raise ValueError(
                f"{len(keys1)} outer keys x span {span} x {na_bound} actors "
                f"overflow the int32 packed cell key"
            )

        r = len(pures)
        na = max(len(actors), n_actors, 1)
        out = cls(
            r, span, cell_cap, na, sibling_cap, deferred_cap, rm_width,
            key_deferred_cap, key_rm_width,
            keys1=keys1, keys2=keys2, actors=actors, values=values,
        )
        top = np.zeros((r, na), np.uint32)
        kid = np.full((r, cell_cap), -1, np.int32)
        act = np.zeros((r, cell_cap), np.int32)
        ctr = np.zeros((r, cell_cap), np.uint32)
        val = np.zeros((r, cell_cap), np.int32)
        clk = np.zeros((r, cell_cap, na), np.uint32)
        valid = np.zeros((r, cell_cap), bool)
        d = deferred_cap
        dcl = np.zeros((r, d, na), np.uint32)
        kidx = np.full((r, d, rm_width), -1, np.int32)
        dvalid = np.zeros((r, d), bool)
        kd = key_deferred_cap
        kcl = np.zeros((r, kd, na), np.uint32)
        kkidx = np.full((r, kd, key_rm_width), -1, np.int32)
        kdvalid = np.zeros((r, kd), bool)
        for i, p in enumerate(pures):
            for actor, c in p.clock.dots.items():
                top[i, actors.id_of(actor)] = c
            cells = []
            inner: dict = {}
            for k1, child in p.entries.items():
                k1i = keys1.id_of(k1)
                for k2, reg in child.entries.items():
                    flat = k1i * span + keys2.id_of(k2)
                    for dd, (clock, v) in reg.vals.items():
                        cells.append(
                            (flat, actors.id_of(dd.actor), dd.counter,
                             clock, v)
                        )
                for clock, k2s in child.deferred.items():
                    inner.setdefault(clock, set()).update(
                        k1i * span + keys2.id_of(k2) for k2 in k2s
                    )
            if len(cells) > cell_cap:
                raise DotCapacityOverflow(
                    f"replica {i}: {len(cells)} live cells > cap {cell_cap}"
                )
            for s, (ki, ai, c, clock, v) in enumerate(
                sorted(cells, key=lambda t: (t[0], t[1]))
            ):
                kid[i, s], act[i, s], ctr[i, s] = ki, ai, c
                val[i, s] = values.id_of(v)
                for actor, cc in clock.dots.items():
                    clk[i, s, actors.id_of(actor)] = cc
                valid[i, s] = True
            if len(inner) > d:
                raise DeferredOverflow(
                    f"replica {i}: {len(inner)} inner parked removes > {d}"
                )
            for s, (clock, flats) in enumerate(inner.items()):
                for actor, cc in clock.dots.items():
                    dcl[i, s, actors.id_of(actor)] = cc
                ids = sorted(flats)
                if len(ids) > rm_width:
                    raise DeferredOverflow(
                        f"replica {i}: inner parked list of {len(ids)} "
                        f"cells > rm_width {rm_width}"
                    )
                kidx[i, s, : len(ids)] = ids
                dvalid[i, s] = True
            if len(p.deferred) > kd:
                raise DeferredOverflow(
                    f"replica {i}: {len(p.deferred)} outer parked removes "
                    f"> {kd}"
                )
            for s, (clock, k1s) in enumerate(p.deferred.items()):
                for actor, cc in clock.dots.items():
                    kcl[i, s, actors.id_of(actor)] = cc
                ids = sorted(keys1.id_of(k1) for k1 in k1s)
                if len(ids) > key_rm_width:
                    raise DeferredOverflow(
                        f"replica {i}: outer parked list of {len(ids)} "
                        f"keys > key_rm_width {key_rm_width}"
                    )
                kkidx[i, s, : len(ids)] = ids
                kdvalid[i, s] = True

        out.state = out.state._replace(
            core=smv.SparseMVMapState(
                top=jnp.asarray(top), kid=jnp.asarray(kid),
                act=jnp.asarray(act), ctr=jnp.asarray(ctr),
                val=jnp.asarray(val), clk=jnp.asarray(clk),
                valid=jnp.asarray(valid), dcl=jnp.asarray(dcl),
                kidx=jnp.asarray(kidx), dvalid=jnp.asarray(dvalid),
            ),
            kcl=jnp.asarray(kcl),
            kidx=jnp.asarray(kkidx),
            kdvalid=jnp.asarray(kdvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Map:
        st = jax.device_get(self._row(self.state, i))
        span = self.span
        out = Map(lambda: Map(MVReg))
        out.clock = VClock(
            {self.actors[a]: int(c)
             for a, c in enumerate(st.core.top) if c > 0}
        )
        for s in np.nonzero(st.core.valid)[0]:
            flat = int(st.core.kid[s])
            k1, k2 = self.keys1[flat // span], self.keys2[flat % span]
            dot = Dot(self.actors[int(st.core.act[s])], int(st.core.ctr[s]))
            clock = VClock(
                {self.actors[a]: int(c)
                 for a, c in enumerate(st.core.clk[s]) if c > 0}
            )
            child = out.entries.get(k1)
            if child is None:
                child = Map(MVReg)
                child.clock = out.clock.clone()
                out.entries[k1] = child
            child.entries.setdefault(k2, MVReg())
            child.entries[k2].vals[dot] = (
                clock, self.values[int(st.core.val[s])]
            )
        # Inner parked removes: split each shared slot back per k1.
        for s in np.nonzero(st.core.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c)
                 for a, c in enumerate(st.core.dcl[s]) if c > 0}
            )
            per_k1: dict = {}
            for flat in st.core.kidx[s]:
                if flat >= 0:
                    per_k1.setdefault(int(flat) // span, set()).add(
                        self.keys2[int(flat) % span]
                    )
            for k1i, k2s in per_k1.items():
                child = out.entries.get(self.keys1[k1i])
                if child is None:
                    continue  # scrubbed dead key (oracle dropped it too)
                child.deferred.setdefault(clock.clone(), set()).update(k2s)
        for s in np.nonzero(st.kdvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c)
                 for a, c in enumerate(st.kcl[s]) if c > 0}
            )
            out.deferred[clock] = {
                self.keys1[int(k)] for k in st.kidx[s] if k >= 0
            }
        return out

    def _k2_id(self, k2) -> int:
        # IndexError (the interner's full-universe signal, raised BEFORE
        # allocating) so elastic.elastic_call can widen the span and
        # retry; a plain ValueError would leave the replica stuck.
        return self.keys2.bounded_intern(k2, self.span, "inner key")

    # ---- op path (CmRDT) ----------------------------------------------
    @transactional_apply("keys1", "keys2", "actors", "values")
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/map.rs ``CmRDT::apply`` routing nested map ops)."""
        if isinstance(op, Nop):
            return
        row = self._row(self.state, replica)
        na = self.state.core.top.shape[-1]
        if isinstance(op, Up):
            strict_validate_dot(
                row.core.top, self.actors, op.dot.actor, op.dot.counter
            )
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            k1i = self.keys1.bounded_intern(op.key, self.n_keys1, "outer key")
            inner = op.op
            if isinstance(inner, Up):
                if inner.dot != op.dot:
                    raise ValueError(
                        "inner Up dot must equal the outer Up dot"
                    )
                if not isinstance(inner.op, Put):
                    raise TypeError(
                        f"innermost op must be an MVReg Put, got {inner.op!r}"
                    )
                flat = k1i * self.span + self._k2_id(inner.key)
                cl = clock_lanes(
                    inner.op.clock, self.actors, na,
                    dtype=self.state.core.top.dtype,
                )
                row, overflow = smv.nest_apply_up_put(
                    self.level, row,
                    jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(flat),
                    jnp.asarray(cl),
                    jnp.asarray(self.values.intern(inner.op.val)),
                )
                if bool(overflow):
                    raise DotCapacityOverflow(
                        f"replica {replica}: cell_cap {self.cell_cap} "
                        f"exceeded"
                    )
            elif isinstance(inner, MapRm):
                cl = clock_lanes(
                    inner.clock, self.actors, na,
                    dtype=self.state.core.top.dtype,
                )
                try:
                    ids = pad_id_list(
                        (k1i * self.span + self._k2_id(k2)
                         for k2 in inner.keyset),
                        width=self.state.core.kidx.shape[-1],
                    )
                except ValueError as e:
                    # A too-narrow parked keylist lane is capacity
                    # pressure: surface the recoverable type so
                    # elastic can widen rm_width and retry.
                    raise DeferredOverflow(str(e)) from e
                row, overflow = self.level.apply_up_rm(
                    row, jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(cl), jnp.asarray(ids), levels_down=1,
                )
                if bool(overflow):
                    raise DeferredOverflow(
                        f"replica {replica}: inner deferred buffer full"
                    )
            else:
                raise TypeError(f"routes Map ops only, got {inner!r}")
        elif isinstance(op, MapRm):
            cl = clock_lanes(
                op.clock, self.actors, na,
                dtype=self.state.core.top.dtype,
            )
            try:
                ids = pad_id_list(
                    (self.keys1.bounded_intern(k1, self.n_keys1, "outer key")
                     for k1 in op.keyset),
                    width=self.state.kidx.shape[-1],
                )
            except ValueError as e:
                # key_rm_width pressure — recoverable, as above.
                raise DeferredOverflow(str(e)) from e
            row, overflow = self.level.rm_parked(
                row, jnp.asarray(cl), jnp.asarray(ids)
            )
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: outer deferred buffer full"
                )
        else:
            raise TypeError(f"not a Map op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    # ---- state path (CvRDT) -------------------------------------------
    def _check_flags(self, flags, what: str) -> None:
        cells, leaf_d, siblings, outer_d = (bool(x) for x in flags)
        if cells:
            raise DotCapacityOverflow(
                f"{what}: cell table full — rebuild with a larger cell_cap"
            )
        if siblings:
            raise SlotOverflow(
                f"{what}: a key exceeds sibling_cap concurrent writers"
            )
        if leaf_d or outer_d:
            raise DeferredOverflow(
                f"{what}: {'inner' if leaf_d else 'outer'} deferred buffer "
                f"full — rebuild with a larger capacity"
            )

    def merge_from(self, dst: int, src: int) -> None:
        metrics.count("sparse_nested_map.merges")
        joined, flags = self.level.join(
            self._row(self.state, dst), self._row(self.state, src)
        )
        self._check_flags(flags, f"merge {src}->{dst}")
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, joined
        )

    def fold(self) -> Map:
        """Full-mesh anti-entropy: join all replicas, return the
        converged oracle-form state."""
        metrics.count("sparse_nested_map.merges", max(self.n_replicas - 1, 0))
        observe_depth("sparse_nested_map", self.state)
        folded, flags = self.level.fold(self.state)
        self._check_flags(flags, "fold")
        tmp = BatchedSparseNestedMap(
            1, self.span, self.cell_cap, self.state.core.top.shape[-1],
            self.sibling_cap, self.state.core.dcl.shape[-2],
            self.state.core.kidx.shape[-1], self.state.kcl.shape[-2],
            self.state.kidx.shape[-1],
            keys1=self.keys1, keys2=self.keys2, actors=self.actors,
            values=self.values,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)

    def nbytes(self) -> int:
        return sum(x.nbytes for x in jax.tree.leaves(self.state))

    # ---- elastic capacity migration (elastic.py) ----------------------
    def widen_capacity(
        self,
        span: int = 0,
        cell_cap: int = 0,
        n_actors: int = 0,
        sibling_cap: int = 0,
        deferred_cap: int = 0,
        rm_width: int = 0,
        key_deferred_cap: int = 0,
        key_rm_width: int = 0,
        n_keys1: int = 0,
    ) -> None:
        """Re-encode the nested cell table into a wider layout in place
        — the sanctioned recovery for every capacity this model bounds.
        A ``span`` widening is the segment-table repack
        (``ops.sparse_nest.widen_span``): flat cell ids and the inner
        parked lists remap ``k1·span + k2`` → ``k1·span' + k2`` on
        device (monotone, so canonical order survives); outer key ids
        are untouched. Everything else is tail padding
        (``ops.sparse_mvmap.widen`` inside ``sparse_nest.widen_level``).
        0 keeps a width; the int32 packed key re-bounds
        ``n_keys1 · span · n_actors`` after the migration."""
        from ..ops import sparse_nest as nest_ops

        old_span = self.span
        nspan = span or old_span
        na = n_actors or self.state.core.top.shape[-1]
        nsib = sibling_cap or self.sibling_cap
        if nsib < self.sibling_cap:
            raise ValueError("widen_capacity cannot shrink sibling_cap")
        cap1 = (2**31 - 1) // max(nspan * na, 1)
        nk1 = n_keys1 or min(self.n_keys1, cap1)
        if n_keys1 and n_keys1 < self.n_keys1:
            raise ValueError("widen_capacity cannot shrink n_keys1")
        if nk1 > cap1 or cap1 < 1:
            raise ValueError(
                f"n_keys1 = {nk1:,} exceeds the int32 packed-key cap "
                f"{cap1:,} at span {nspan} x {na} actors"
            )
        if nk1 < len(self.keys1):
            raise ValueError(
                f"n_keys1 = {nk1} would orphan {len(self.keys1)} "
                f"already-interned outer keys"
            )
        state = self.state
        if nspan != old_span:
            if len(self.keys2) > 0 and nspan < len(self.keys2):
                raise ValueError(
                    f"span {nspan} below {len(self.keys2)} interned inner keys"
                )
            state = nest_ops.widen_span(state, old_span, nspan)
        state = nest_ops.widen_level(
            state,
            lambda core: smv.widen(
                core, cell_cap, n_actors, deferred_cap, rm_width
            ),
            key_deferred_cap,
            key_rm_width,
            n_actors,
        )
        self.state = state
        self.n_keys1 = nk1
        self.sibling_cap = nsib
        if nspan != old_span or nsib != self.level.core.sibling_cap:
            self.level = smv.level_map_mvreg(nspan, nsib)

    def narrow_capacity(
        self,
        span: int = 0,
        cell_cap: int = 0,
        n_actors: int = 0,
        deferred_cap: int = 0,
        rm_width: int = 0,
        key_deferred_cap: int = 0,
        key_rm_width: int = 0,
    ) -> None:
        """The inverse migration — slice the nested cell table down in
        place (elastic.shrink drives this under the hysteresis policy).
        A ``span`` narrowing is ``ops.sparse_nest.narrow_span`` (flat
        ids remap; refused when any live offset does not fit);
        everything else is tail slicing through
        ``sparse_nest.narrow_level`` riding ``sparse_mvmap.narrow`` —
        each kernel refuses when occupancy does not fit. 0 keeps a
        width."""
        from ..ops import sparse_nest as nest_ops

        old_span = self.span
        nspan = span or old_span
        if nspan != old_span:
            if len(self.keys2) > 0 and nspan < len(self.keys2):
                raise ValueError(
                    f"narrow refused: span {nspan} below "
                    f"{len(self.keys2)} interned inner keys"
                )
            self.state = nest_ops.narrow_span(self.state, old_span, nspan)
        if n_actors and n_actors < len(self.actors):
            raise ValueError(
                f"narrow refused: {len(self.actors)} actors interned > "
                f"target n_actors {n_actors}"
            )
        self.state = nest_ops.narrow_level(
            self.state,
            lambda core: smv.narrow(
                core, cell_cap, n_actors, deferred_cap, rm_width
            ),
            key_deferred_cap,
            key_rm_width,
            n_actors,
        )
        if nspan != old_span:
            self.level = smv.level_map_mvreg(nspan, self.sibling_cap)
