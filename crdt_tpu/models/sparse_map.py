"""BatchedSparseMapOrswot — N segment-encoded ``Map<K, Orswot>``
replicas on device.

The sparse counterpart of :class:`.map_nested.BatchedMapOrswot` for key
universes where the dense K×M slab stops scaling (VERDICT r04 Missing
#2; reference: src/map.rs ``Map<K, V: Val<A>, A>``): state tracks LIVE
(key, member, actor) cells plus parked-remove LISTS, never a K×M cube.
Flattening matches the dense model (cell id = key_id · span +
member_id, global member interner) so the two backends are directly
comparable; conversion to/from the oracle is lossless and the
bit-identical A/B gates in tests/test_sparse_nest.py mirror the dense
suite's.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import sparse_nest as nest
from ..ops import sparse_orswot as sp
from ..pure.map import Map, MapRm, Nop, Up
from ..pure.orswot import Add as OrswotAdd, Orswot, Rm as OrswotRm
from ..utils import Interner, clock_lanes, pad_id_list, transactional_apply
from ..utils.metrics import metrics, observe_depth
from ..vclock import VClock
from .orswot import DeferredOverflow
from .sparse_orswot import DotCapacityOverflow
from .validation import strict_validate_dot


class BatchedSparseMapOrswot:
    def __init__(
        self,
        n_replicas: int,
        span: int,
        dot_cap: int,
        n_actors: int,
        deferred_cap: int = 4,
        rm_width: int = 8,
        key_deferred_cap: int = 4,
        key_rm_width: int = 8,
        keys: Optional[Interner] = None,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
    ):
        self.keys = keys if keys is not None else Interner()
        self.members = members if members is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.level = nest.level_map_orswot(span)
        self.state = nest.empty_map_orswot(
            span, dot_cap, n_actors, deferred_cap, rm_width,
            key_deferred_cap, key_rm_width, batch=(n_replicas,),
        )

    @property
    def n_replicas(self) -> int:
        return self.state.core.top.shape[0]

    @property
    def span(self) -> int:
        return self.level.span

    @property
    def dot_cap(self) -> int:
        return self.state.core.eid.shape[-1]

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Map],
        span: int = 64,
        dot_cap: int = 256,
        deferred_cap: int = 4,
        rm_width: int = 8,
        key_deferred_cap: int = 4,
        key_rm_width: int = 8,
        keys: Optional[Interner] = None,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        n_actors: int = 1,
    ) -> "BatchedSparseMapOrswot":
        keys = keys if keys is not None else Interner()
        members = members if members is not None else Interner()
        actors = actors if actors is not None else Interner()
        for p in pures:
            for actor in p.clock.dots:
                actors.intern(actor)
            for k, child in p.entries.items():
                keys.intern(k)
                if not isinstance(child, Orswot):
                    raise TypeError(
                        f"children must be Orswot, got {type(child)}"
                    )
                if child.clock != p.clock:
                    raise ValueError(
                        f"child at {k!r} violates the covered invariant "
                        f"(child clock != map clock); not a composed state"
                    )
                for m, clock in child.entries.items():
                    members.intern(m)
                    for actor in clock.dots:
                        actors.intern(actor)
                for clock, ms in child.deferred.items():
                    for actor in clock.dots:
                        actors.intern(actor)
                    for m in ms:
                        members.intern(m)
            for clock, ks in p.deferred.items():
                for actor in clock.dots:
                    actors.intern(actor)
                for k in ks:
                    keys.intern(k)
        if len(members) > span:
            raise ValueError(
                f"{len(members)} members exceed the per-key span {span}"
            )

        r = len(pures)
        na = max(len(actors), n_actors, 1)
        out = cls(
            r, span, dot_cap, na, deferred_cap, rm_width,
            key_deferred_cap, key_rm_width,
            keys=keys, members=members, actors=actors,
        )
        top = np.zeros((r, na), np.uint32)
        eid = np.full((r, dot_cap), -1, np.int32)
        act = np.zeros((r, dot_cap), np.int32)
        ctr = np.zeros((r, dot_cap), np.uint32)
        valid = np.zeros((r, dot_cap), bool)
        dcl = np.zeros((r, deferred_cap, na), np.uint32)
        didx = np.full((r, deferred_cap, rm_width), -1, np.int32)
        dvalid = np.zeros((r, deferred_cap), bool)
        kcl = np.zeros((r, key_deferred_cap, na), np.uint32)
        kidx = np.full((r, key_deferred_cap, key_rm_width), -1, np.int32)
        kdvalid = np.zeros((r, key_deferred_cap), bool)
        for i, p in enumerate(pures):
            for actor, c in p.clock.dots.items():
                top[i, actors.id_of(actor)] = c
            cells = sorted(
                (
                    keys.id_of(k) * span + members.id_of(m),
                    actors.id_of(a),
                    c,
                )
                for k, child in p.entries.items()
                for m, clock in child.entries.items()
                for a, c in clock.dots.items()
            )
            if len(cells) > dot_cap:
                raise DotCapacityOverflow(
                    f"replica {i}: {len(cells)} live cells > dot_cap {dot_cap}"
                )
            for s, (e, a, c) in enumerate(cells):
                eid[i, s], act[i, s], ctr[i, s], valid[i, s] = e, a, c, True
            # Inner (per-child) parked removes: equal clocks union into
            # shared slots (what a join produces); to_pure splits back.
            inner: dict = {}
            for k, child in p.entries.items():
                ki = keys.id_of(k)
                for clock, ms in child.deferred.items():
                    inner.setdefault(clock, set()).update(
                        ki * span + members.id_of(m) for m in ms
                    )
            if len(inner) > deferred_cap:
                raise DeferredOverflow(
                    f"replica {i}: {len(inner)} inner parked removes; "
                    f"capacity is {deferred_cap}"
                )
            for s, (clock, ids) in enumerate(inner.items()):
                ids = sorted(ids)
                if len(ids) > rm_width:
                    raise DeferredOverflow(
                        f"replica {i} slot {s}: {len(ids)} parked cells "
                        f"> rm_width {rm_width}"
                    )
                for actor, c in clock.dots.items():
                    dcl[i, s, actors.id_of(actor)] = c
                didx[i, s, : len(ids)] = ids
                dvalid[i, s] = True
            if len(p.deferred) > key_deferred_cap:
                raise DeferredOverflow(
                    f"replica {i}: {len(p.deferred)} outer parked removes; "
                    f"capacity is {key_deferred_cap}"
                )
            for s, (clock, ks) in enumerate(p.deferred.items()):
                ids = sorted(keys.id_of(k) for k in ks)
                if len(ids) > key_rm_width:
                    raise DeferredOverflow(
                        f"replica {i} slot {s}: {len(ids)} parked keys "
                        f"> key_rm_width {key_rm_width}"
                    )
                for actor, c in clock.dots.items():
                    kcl[i, s, actors.id_of(actor)] = c
                kidx[i, s, : len(ids)] = ids
                kdvalid[i, s] = True
        core = sp.SparseOrswotState(
            top=jnp.asarray(top), eid=jnp.asarray(eid), act=jnp.asarray(act),
            ctr=jnp.asarray(ctr), valid=jnp.asarray(valid),
            dcl=jnp.asarray(dcl), didx=jnp.asarray(didx),
            dvalid=jnp.asarray(dvalid),
        )
        out.state = nest.SparseNestState(
            core=core, kcl=jnp.asarray(kcl), kidx=jnp.asarray(kidx),
            kdvalid=jnp.asarray(kdvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Map:
        st = jax.device_get(self._row(self.state, i))
        span = self.span
        out = Map(Orswot)
        out.clock = VClock(
            {self.actors[a]: int(c) for a, c in enumerate(st.core.top) if c > 0}
        )
        for s in np.nonzero(st.core.valid)[0]:
            e = int(st.core.eid[s])
            k, m = self.keys[e // span], self.members[e % span]
            child = out.entries.get(k)
            if child is None:
                child = Orswot()
                child.clock = out.clock.clone()
                out.entries[k] = child
            entry = child.entries.setdefault(m, VClock())
            entry.dots[self.actors[int(st.core.act[s])]] = int(st.core.ctr[s])
        # Inner parked removes: split each shared slot back per key;
        # dead keys were scrubbed on device (the oracle dropped them too).
        for s in np.nonzero(st.core.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c)
                 for a, c in enumerate(st.core.dcl[s]) if c > 0}
            )
            for e in st.core.didx[s]:
                if e < 0:
                    continue
                child = out.entries.get(self.keys[int(e) // span])
                if child is None:
                    continue
                child.deferred.setdefault(clock.clone(), set()).add(
                    self.members[int(e) % span]
                )
        for s in np.nonzero(st.kdvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c)
                 for a, c in enumerate(st.kcl[s]) if c > 0}
            )
            # Equal-clock slots union into ONE oracle entry (the sparse
            # form may split a clock's list across slots past rm_width).
            out.deferred.setdefault(clock, set()).update(
                self.keys[int(k)] for k in st.kidx[s] if k >= 0
            )
        return out

    # ---- op path (CmRDT) ----------------------------------------------
    def _ids(self, pairs, width: Optional[int] = None) -> np.ndarray:
        """Flattened (key, member) cell ids, fixed width (power-of-two
        bucket ≥ 8 when unconstrained, to bound jit retraces)."""
        return pad_id_list(pairs, width)

    @transactional_apply("keys", "members", "actors")
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/map.rs ``CmRDT::apply`` routing orswot child ops)."""
        if isinstance(op, Nop):
            return
        row = self._row(self.state, replica)
        na = self.state.core.top.shape[-1]
        span = self.span
        if isinstance(op, Up):
            strict_validate_dot(
                row.core.top, self.actors, op.dot.actor, op.dot.counter
            )
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            kid = self.keys.intern(op.key)
            if isinstance(op.op, OrswotAdd):
                if op.op.dot != op.dot:
                    raise ValueError(
                        "inner add dot must equal the Up dot (one AddCtx)"
                    )
                eids = self._ids(
                    kid * span + self._member_id(m) for m in op.op.members
                )
                row, overflow = self.level.apply_up_add(
                    row, jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(eids),
                )
                if bool(overflow):
                    raise DotCapacityOverflow(
                        f"replica {replica}: dot_cap {self.dot_cap} exceeded"
                    )
            elif isinstance(op.op, OrswotRm):
                clock = clock_lanes(op.op.clock, self.actors, na)
                ids = self._ids(
                    (kid * span + self._member_id(m) for m in op.op.members),
                    width=self.state.core.didx.shape[-1],
                )
                row, overflow = self.level.apply_up_rm(
                    row, jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(clock), jnp.asarray(ids), levels_down=1,
                )
                if bool(overflow):
                    raise DeferredOverflow(
                        f"replica {replica}: inner deferred buffer full "
                        f"(cap {self.state.core.dvalid.shape[-1]})"
                    )
            else:
                raise TypeError(
                    f"routes Orswot ops only, got {op.op!r}"
                )
        elif isinstance(op, MapRm):
            clock = clock_lanes(op.clock, self.actors, na)
            ids = self._ids(
                (self.keys.intern(k) for k in op.keyset),
                width=self.state.kidx.shape[-1],
            )
            row, overflow = self.level.rm_parked(
                row, jnp.asarray(clock), jnp.asarray(ids)
            )
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: outer deferred buffer full "
                    f"(cap {self.state.kdvalid.shape[-1]})"
                )
        else:
            raise TypeError(f"not a Map op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    def _member_id(self, m) -> int:
        mid = self.members.intern(m)
        if mid >= self.span:
            raise ValueError(
                f"member universe exceeded the per-key span {self.span}"
            )
        return mid

    # ---- state path (CvRDT) -------------------------------------------
    def _check_flags(self, flags, what: str) -> None:
        if bool(flags[0]):
            raise DotCapacityOverflow(
                f"{what}: survivor cells exceed dot_cap {self.dot_cap}"
            )
        if bool(flags[1]) or bool(flags[2]):
            raise DeferredOverflow(
                f"{what}: {'inner' if bool(flags[1]) else 'outer'} deferred "
                f"buffer full — rebuild with a larger capacity"
            )

    def merge_from(self, dst: int, src: int) -> None:
        metrics.count("sparse_map_orswot.merges")
        joined, flags = self.level.join(
            self._row(self.state, dst), self._row(self.state, src)
        )
        self._check_flags(flags, f"merge {src}->{dst}")
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, joined
        )

    def fold(self) -> Map:
        """Full-mesh anti-entropy: join all replicas, return the
        converged oracle-form state."""
        metrics.count("sparse_map_orswot.merges", max(self.n_replicas - 1, 0))
        observe_depth("sparse_map_orswot", self.state)
        folded, flags = self.level.fold(self.state)
        self._check_flags(flags, "fold")
        tmp = BatchedSparseMapOrswot(
            1, self.span, self.dot_cap, self.state.core.top.shape[-1],
            self.state.core.dcl.shape[-2], self.state.core.didx.shape[-1],
            self.state.kcl.shape[-2], self.state.kidx.shape[-1],
            keys=self.keys, members=self.members, actors=self.actors,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)
