"""Val-generic device Maps: ``Map<K, Orswot<M>>`` and
``Map<K1, Map<K2, MVReg>>`` replicas on device.

Oracle: ``crdt_tpu.pure.map.Map`` with ``Orswot`` / nested ``Map``
children (reference: src/map.rs ``Map<K, V: Val<A>, A>`` — the
``V: Val<A>`` genericity beyond the MVReg specialisation of
models/map.py). Device form per ops/map_orswot.py and ops/map_map.py:
the causal-composition invariant (every child top == the map top)
collapses nested state to ONE slab over the product space (K × M member
dots, or K1 × K2 content slots) plus a second (outer) deferred buffer —
slab composition, not trace-time recursion (SURVEY.md §7.1).

Conversions are lossless — birth clocks / content witnesses, inner
(per-child) parked removes, outer parked keyset-removes — which the
bit-identical A/B gates in tests/test_models_map_nested.py exercise.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dot import Dot
from ..ops import map_map as nested_ops
from ..ops import map_orswot as ops
from ..ops import mvreg as mv_ops
from ..pure.map import Map, MapRm, Nop, Up
from ..pure.mvreg import MVReg, Put
from ..pure.orswot import Add as OrswotAdd, Orswot, Rm as OrswotRm
from ..utils import Interner, clock_lanes, transactional_apply
from ..utils.metrics import metrics, observe_depth
from ..vclock import VClock
from .orswot import DeferredOverflow
from .registers import SlotOverflow
from .validation import strict_validate_dot


class BatchedMapOrswot:
    def __init__(
        self,
        n_replicas: int,
        n_keys: int,
        n_members: int,
        n_actors: int,
        deferred_cap: int = 4,
        keys: Optional[Interner] = None,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
    ):
        self.keys = keys if keys is not None else Interner()
        self.members = members if members is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.state = ops.empty(
            n_keys, n_members, n_actors, deferred_cap, batch=(n_replicas,)
        )

    @property
    def n_replicas(self) -> int:
        return self.state.core.top.shape[0]

    @property
    def n_keys(self) -> int:
        return self.state.kdkeys.shape[-1]

    @property
    def n_members(self) -> int:
        return self.state.core.ctr.shape[-2] // self.n_keys

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Map],
        deferred_cap: int = 4,
        keys: Optional[Interner] = None,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        n_keys: int = 1,
        n_members: int = 1,
        n_actors: int = 1,
    ) -> "BatchedMapOrswot":
        keys = keys if keys is not None else Interner()
        members = members if members is not None else Interner()
        actors = actors if actors is not None else Interner()
        for p in pures:
            for actor in p.clock.dots:
                actors.intern(actor)
            for k, child in p.entries.items():
                keys.intern(k)
                if not isinstance(child, Orswot):
                    raise TypeError(
                        f"BatchedMapOrswot children must be Orswot, got {type(child)}"
                    )
                if child.clock != p.clock:
                    raise ValueError(
                        f"child at {k!r} violates the covered invariant "
                        f"(child clock != map clock); not a composed state"
                    )
                for m, clock in child.entries.items():
                    members.intern(m)
                    for actor in clock.dots:
                        actors.intern(actor)
                for clock, ms in child.deferred.items():
                    for actor in clock.dots:
                        actors.intern(actor)
                    for m in ms:
                        members.intern(m)
            for clock, ks in p.deferred.items():
                for actor in clock.dots:
                    actors.intern(actor)
                for k in ks:
                    keys.intern(k)

        r = len(pures)
        # Lane counts: what the pures need, with caller-given floors so a
        # model built from empty replicas still has room to grow via ops.
        nk = max(len(keys), n_keys, 1)
        nm = max(len(members), n_members, 1)
        na = max(len(actors), n_actors, 1)
        out = cls(
            r, nk, nm, na, deferred_cap,
            keys=keys, members=members, actors=actors,
        )
        d = deferred_cap
        top = np.zeros((r, na), np.uint32)
        ctr = np.zeros((r, nk * nm, na), np.uint32)
        dcl = np.zeros((r, d, na), np.uint32)
        dmask = np.zeros((r, d, nk * nm), bool)
        dvalid = np.zeros((r, d), bool)
        kdcl = np.zeros((r, d, na), np.uint32)
        kdkeys = np.zeros((r, d, nk), bool)
        kdvalid = np.zeros((r, d), bool)
        for i, p in enumerate(pures):
            for actor, c in p.clock.dots.items():
                top[i, actors.id_of(actor)] = c
            # Inner parked removes: pure keeps them per child; the shared
            # device buffer unions equal clocks (what a join produces) —
            # to_pure splits them back per key.
            inner: dict = {}
            for k, child in p.entries.items():
                ki = keys.id_of(k)
                for m, clock in child.entries.items():
                    mi = members.id_of(m)
                    for actor, c in clock.dots.items():
                        ctr[i, ki * nm + mi, actors.id_of(actor)] = c
                for clock, ms in child.deferred.items():
                    inner.setdefault(clock, set()).update(
                        ki * nm + members.id_of(m) for m in ms
                    )
            if len(inner) > d:
                raise ValueError(
                    f"replica {i}: {len(inner)} inner parked removes; "
                    f"capacity is {d}"
                )
            for s, (clock, cells) in enumerate(inner.items()):
                for actor, c in clock.dots.items():
                    dcl[i, s, actors.id_of(actor)] = c
                for cell in cells:
                    dmask[i, s, cell] = True
                dvalid[i, s] = True
            if len(p.deferred) > d:
                raise ValueError(
                    f"replica {i}: {len(p.deferred)} outer parked removes; "
                    f"capacity is {d}"
                )
            for s, (clock, ks) in enumerate(p.deferred.items()):
                for actor, c in clock.dots.items():
                    kdcl[i, s, actors.id_of(actor)] = c
                for k in ks:
                    kdkeys[i, s, keys.id_of(k)] = True
                kdvalid[i, s] = True

        core = out.state.core._replace(
            top=jnp.asarray(top),
            ctr=jnp.asarray(ctr),
            dcl=jnp.asarray(dcl),
            dmask=jnp.asarray(dmask),
            dvalid=jnp.asarray(dvalid),
        )
        out.state = ops.MapOrswotState(
            core=core,
            kdcl=jnp.asarray(kdcl),
            kdkeys=jnp.asarray(kdkeys),
            kdvalid=jnp.asarray(kdvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Map:
        st = jax.device_get(self._row(self.state, i))
        nk, nm = self.n_keys, self.n_members
        out = Map(Orswot)
        out.clock = VClock(
            {self.actors[a]: int(c) for a, c in enumerate(st.core.top) if c > 0}
        )
        ctr = st.core.ctr.reshape(nk, nm, -1)
        for ki in np.nonzero(ctr.any(axis=(1, 2)))[0]:
            child = Orswot()
            child.clock = out.clock.clone()
            for mi in np.nonzero(ctr[ki].any(axis=-1))[0]:
                child.entries[self.members[int(mi)]] = VClock(
                    {
                        self.actors[a]: int(c)
                        for a, c in enumerate(ctr[ki, mi])
                        if c > 0
                    }
                )
            out.entries[self.keys[int(ki)]] = child
        # Inner parked removes: split each shared slot back per key.
        for s in np.nonzero(st.core.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.core.dcl[s]) if c > 0}
            )
            mask = st.core.dmask[s].reshape(nk, nm)
            for ki in np.nonzero(mask.any(axis=-1))[0]:
                child = out.entries.get(self.keys[int(ki)])
                if child is None:
                    continue  # scrubbed dead key (oracle dropped it too)
                child.deferred.setdefault(clock.clone(), set()).update(
                    self.members[int(mi)] for mi in np.nonzero(mask[ki])[0]
                )
        for s in np.nonzero(st.kdvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.kdcl[s]) if c > 0}
            )
            out.deferred[clock] = {
                self.keys[int(k)] for k in np.nonzero(st.kdkeys[s])[0]
            }
        return out

    # ---- op path (CmRDT) ----------------------------------------------
    @transactional_apply("keys", "members", "actors")
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/map.rs ``CmRDT::apply`` routing orswot child ops)."""
        if isinstance(op, Nop):
            return
        row = self._row(self.state, replica)
        na, nk, nm = self.state.core.top.shape[-1], self.n_keys, self.n_members
        if isinstance(op, Up):
            strict_validate_dot(row.core.top, self.actors, op.dot.actor, op.dot.counter)
            kid = self.keys.bounded_intern(op.key, nk, "key")
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            if isinstance(op.op, OrswotAdd):
                if op.op.dot != op.dot:
                    raise ValueError(
                        "inner add dot must equal the Up dot (one AddCtx)"
                    )
                mask = np.zeros((nm,), bool)
                for m in op.op.members:
                    mask[self.members.bounded_intern(m, nm, "member")] = True
                row = ops.apply_member_add(
                    row,
                    jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(kid),
                    jnp.asarray(mask),
                )
            elif isinstance(op.op, OrswotRm):
                clock = clock_lanes(op.op.clock, self.actors, na)
                mask = np.zeros((nm,), bool)
                for m in op.op.members:
                    mask[self.members.bounded_intern(m, nm, "member")] = True
                row, overflow = ops.apply_member_rm(
                    row,
                    jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(kid),
                    jnp.asarray(clock),
                    jnp.asarray(mask),
                )
                if bool(overflow):
                    raise DeferredOverflow(
                        f"replica {replica}: inner deferred buffer full "
                        f"(cap {self.state.core.dvalid.shape[-1]})"
                    )
            else:
                raise TypeError(
                    f"BatchedMapOrswot routes Orswot ops only, got {op.op!r}"
                )
        elif isinstance(op, MapRm):
            clock = clock_lanes(op.clock, self.actors, na)
            mask = np.zeros((nk,), bool)
            for k in op.keyset:
                mask[self.keys.bounded_intern(k, nk, "key")] = True
            row, overflow = ops.apply_key_rm(row, jnp.asarray(clock), jnp.asarray(mask))
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: outer deferred buffer full "
                    f"(cap {self.state.kdvalid.shape[-1]})"
                )
        else:
            raise TypeError(f"not a Map op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    # ---- state path (CvRDT) -------------------------------------------
    def _check_flags(self, flags, what: str) -> None:
        inner, outer = (bool(x) for x in flags)
        if inner or outer:
            raise DeferredOverflow(
                f"{what}: {'inner' if inner else 'outer'} deferred buffer "
                f"full — rebuild with a larger deferred_cap"
            )

    def merge_from(self, dst: int, src: int) -> None:
        metrics.count("map_orswot.merges")
        joined, flags = ops.join(
            self._row(self.state, dst), self._row(self.state, src)
        )
        self._check_flags(flags, f"merge {src}->{dst}")
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, joined
        )

    def fold(self) -> Map:
        """Full-mesh anti-entropy: join all replicas, return the converged
        oracle-form state."""
        metrics.count("map_orswot.merges", max(self.n_replicas - 1, 0))
        observe_depth("map_orswot", self.state)
        folded, flags = ops.fold(self.state)
        self._check_flags(flags, "fold")
        tmp = BatchedMapOrswot(
            1, self.n_keys, self.n_members,
            self.state.core.top.shape[-1],
            self.state.kdcl.shape[-2],
            keys=self.keys, members=self.members, actors=self.actors,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)

    def keys_of(self, i: int) -> frozenset:
        nk, nm = self.n_keys, self.n_members
        ctr = np.asarray(self.state.core.ctr[i]).reshape(nk, nm, -1)
        return frozenset(
            self.keys[int(k)] for k in np.nonzero(ctr.any(axis=(1, 2)))[0]
        )


class BatchedNestedMap:
    """N dense ``Map<K1, Map<K2, MVReg>>`` replicas (ops/map_map.py)."""

    def __init__(
        self,
        n_replicas: int,
        n_keys1: int,
        n_keys2: int,
        n_actors: int,
        sibling_cap: int = 4,
        deferred_cap: int = 4,
        keys1: Optional[Interner] = None,
        keys2: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
    ):
        self.keys1 = keys1 if keys1 is not None else Interner()
        self.keys2 = keys2 if keys2 is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.values = values if values is not None else Interner()
        self.state = nested_ops.empty(
            n_keys1, n_keys2, n_actors, sibling_cap, deferred_cap,
            batch=(n_replicas,),
        )

    @property
    def n_replicas(self) -> int:
        return self.state.m.top.shape[0]

    @property
    def n_keys1(self) -> int:
        return self.state.odkeys.shape[-1]

    @property
    def n_keys2(self) -> int:
        return self.state.m.dkeys.shape[-1] // self.n_keys1

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Map],
        sibling_cap: int = 4,
        deferred_cap: int = 4,
        keys1: Optional[Interner] = None,
        keys2: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
        n_keys1: int = 1,
        n_keys2: int = 1,
        n_actors: int = 1,
    ) -> "BatchedNestedMap":
        keys1 = keys1 if keys1 is not None else Interner()
        keys2 = keys2 if keys2 is not None else Interner()
        actors = actors if actors is not None else Interner()
        values = values if values is not None else Interner()
        for p in pures:
            for actor in p.clock.dots:
                actors.intern(actor)
            for k1, child in p.entries.items():
                keys1.intern(k1)
                if not isinstance(child, Map):
                    raise TypeError(
                        f"BatchedNestedMap children must be Map, got {type(child)}"
                    )
                if child.clock != p.clock:
                    raise ValueError(
                        f"child at {k1!r} violates the covered invariant "
                        f"(child clock != map clock); not a composed state"
                    )
                for k2, reg in child.entries.items():
                    keys2.intern(k2)
                    if not isinstance(reg, MVReg):
                        raise TypeError(
                            f"inner children must be MVReg, got {type(reg)}"
                        )
                    for d, (clock, v) in reg.vals.items():
                        actors.intern(d.actor)
                        for actor in clock.dots:
                            actors.intern(actor)
                        values.intern(v)
                for clock, k2s in child.deferred.items():
                    for actor in clock.dots:
                        actors.intern(actor)
                    for k2 in k2s:
                        keys2.intern(k2)
            for clock, k1s in p.deferred.items():
                for actor in clock.dots:
                    actors.intern(actor)
                for k1 in k1s:
                    keys1.intern(k1)

        r = len(pures)
        # Lane counts: what the pures need, with caller-given floors so a
        # model built from empty replicas still has room to grow via ops.
        nk1 = max(len(keys1), n_keys1, 1)
        nk2 = max(len(keys2), n_keys2, 1)
        na = max(len(actors), n_actors, 1)
        out = cls(
            r, nk1, nk2, na, sibling_cap, deferred_cap,
            keys1=keys1, keys2=keys2, actors=actors, values=values,
        )
        d, s = deferred_cap, sibling_cap
        nk = nk1 * nk2
        top = np.zeros((r, na), np.uint32)
        cact = np.zeros((r, nk, s), np.int32)
        cctr = np.zeros((r, nk, s), np.uint32)
        cclk = np.zeros((r, nk, s, na), np.uint32)
        cval = np.zeros((r, nk, s), np.int32)
        cvalid = np.zeros((r, nk, s), bool)
        dcl = np.zeros((r, d, na), np.uint32)
        dkeys = np.zeros((r, d, nk), bool)
        dvalid = np.zeros((r, d), bool)
        odcl = np.zeros((r, d, na), np.uint32)
        odkeys = np.zeros((r, d, nk1), bool)
        odvalid = np.zeros((r, d), bool)
        for i, p in enumerate(pures):
            for actor, c in p.clock.dots.items():
                top[i, actors.id_of(actor)] = c
            inner: dict = {}
            for k1, child in p.entries.items():
                k1i = keys1.id_of(k1)
                for k2, reg in child.entries.items():
                    ki = k1i * nk2 + keys2.id_of(k2)
                    if len(reg.vals) > s:
                        raise ValueError(
                            f"replica {i} key ({k1!r},{k2!r}): "
                            f"{len(reg.vals)} siblings; capacity is {s}"
                        )
                    for si, (dot, (clock, v)) in enumerate(
                        sorted(
                            reg.vals.items(),
                            key=lambda kv: (
                                actors.id_of(kv[0].actor), kv[0].counter,
                            ),
                        )
                    ):
                        cact[i, ki, si] = actors.id_of(dot.actor)
                        cctr[i, ki, si] = dot.counter
                        for actor, c in clock.dots.items():
                            cclk[i, ki, si, actors.id_of(actor)] = c
                        cval[i, ki, si] = values.id_of(v)
                        cvalid[i, ki, si] = True
                for clock, k2s in child.deferred.items():
                    inner.setdefault(clock, set()).update(
                        k1i * nk2 + keys2.id_of(k2) for k2 in k2s
                    )
            if len(inner) > d:
                raise ValueError(
                    f"replica {i}: {len(inner)} inner parked removes; "
                    f"capacity is {d}"
                )
            for si, (clock, cells) in enumerate(inner.items()):
                for actor, c in clock.dots.items():
                    dcl[i, si, actors.id_of(actor)] = c
                for cell in cells:
                    dkeys[i, si, cell] = True
                dvalid[i, si] = True
            if len(p.deferred) > d:
                raise ValueError(
                    f"replica {i}: {len(p.deferred)} outer parked removes; "
                    f"capacity is {d}"
                )
            for si, (clock, k1s) in enumerate(p.deferred.items()):
                for actor, c in clock.dots.items():
                    odcl[i, si, actors.id_of(actor)] = c
                for k1 in k1s:
                    odkeys[i, si, keys1.id_of(k1)] = True
                odvalid[i, si] = True

        out.state = nested_ops.NestedMapState(
            m=out.state.m._replace(
                top=jnp.asarray(top),
                child=mv_ops.MVRegState(
                    wact=jnp.asarray(cact),
                    wctr=jnp.asarray(cctr),
                    clk=jnp.asarray(cclk),
                    val=jnp.asarray(cval),
                    valid=jnp.asarray(cvalid),
                ),
                dcl=jnp.asarray(dcl),
                dkeys=jnp.asarray(dkeys),
                dvalid=jnp.asarray(dvalid),
            ),
            odcl=jnp.asarray(odcl),
            odkeys=jnp.asarray(odkeys),
            odvalid=jnp.asarray(odvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Map:
        st = jax.device_get(self._row(self.state, i))
        nk1, nk2 = self.n_keys1, self.n_keys2
        inner_map = lambda: Map(MVReg)
        out = Map(inner_map)
        out.clock = VClock(
            {self.actors[a]: int(c) for a, c in enumerate(st.m.top) if c > 0}
        )
        valid = st.m.child.valid.reshape(nk1, nk2, -1)
        for k1i in np.nonzero(valid.any(axis=(1, 2)))[0]:
            child = Map(MVReg)
            child.clock = out.clock.clone()
            for k2i in np.nonzero(valid[k1i].any(axis=-1))[0]:
                ki = int(k1i) * nk2 + int(k2i)
                vals = {}
                for si in np.nonzero(st.m.child.valid[ki])[0]:
                    dot = Dot(
                        self.actors[int(st.m.child.wact[ki, si])],
                        int(st.m.child.wctr[ki, si]),
                    )
                    clock = VClock(
                        {
                            self.actors[a]: int(c)
                            for a, c in enumerate(st.m.child.clk[ki, si])
                            if c > 0
                        }
                    )
                    vals[dot] = (clock, self.values[int(st.m.child.val[ki, si])])
                child.entries[self.keys2[int(k2i)]] = MVReg(vals)
            out.entries[self.keys1[int(k1i)]] = child
        # Inner parked removes: split each shared slot back per k1.
        for si in np.nonzero(st.m.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.m.dcl[si]) if c > 0}
            )
            mask = st.m.dkeys[si].reshape(nk1, nk2)
            for k1i in np.nonzero(mask.any(axis=-1))[0]:
                child = out.entries.get(self.keys1[int(k1i)])
                if child is None:
                    continue  # scrubbed dead key (oracle dropped it too)
                child.deferred.setdefault(clock.clone(), set()).update(
                    self.keys2[int(k2i)] for k2i in np.nonzero(mask[k1i])[0]
                )
        for si in np.nonzero(st.odvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.odcl[si]) if c > 0}
            )
            out.deferred[clock] = {
                self.keys1[int(k)] for k in np.nonzero(st.odkeys[si])[0]
            }
        return out

    # ---- op path (CmRDT) ----------------------------------------------
    @transactional_apply("keys1", "keys2", "actors", "values")
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/map.rs ``CmRDT::apply`` routing nested map ops)."""
        if isinstance(op, Nop):
            return
        row = self._row(self.state, replica)
        na = self.state.m.top.shape[-1]
        nk1, nk2 = self.n_keys1, self.n_keys2
        if isinstance(op, Up):
            strict_validate_dot(row.m.top, self.actors, op.dot.actor, op.dot.counter)
            k1id = self.keys1.bounded_intern(op.key, nk1, "outer key")
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            inner = op.op
            if isinstance(inner, Up):
                if inner.dot != op.dot:
                    raise ValueError(
                        "inner Up dot must equal the outer Up dot (one AddCtx)"
                    )
                if not isinstance(inner.op, Put):
                    raise TypeError(
                        f"innermost op must be an MVReg Put, got {inner.op!r}"
                    )
                k2id = self.keys2.bounded_intern(inner.key, nk2, "inner key")
                clock = clock_lanes(inner.op.clock, self.actors, na)
                row, overflow = nested_ops.apply_put(
                    row,
                    jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(k1id),
                    jnp.asarray(k2id),
                    jnp.asarray(clock),
                    jnp.asarray(self.values.intern(inner.op.val)),
                )
                if bool(overflow):
                    raise SlotOverflow(
                        f"replica {replica}: sibling slab full at "
                        f"({op.key!r},{inner.key!r})"
                    )
            elif isinstance(inner, MapRm):
                clock = clock_lanes(inner.clock, self.actors, na)
                mask = np.zeros((nk2,), bool)
                for k2 in inner.keyset:
                    mask[self.keys2.bounded_intern(k2, nk2, "inner key")] = True
                row, overflow = nested_ops.apply_inner_rm(
                    row,
                    jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(k1id),
                    jnp.asarray(clock),
                    jnp.asarray(mask),
                )
                if bool(overflow):
                    raise DeferredOverflow(
                        f"replica {replica}: inner deferred buffer full "
                        f"(cap {self.state.m.dvalid.shape[-1]})"
                    )
            else:
                raise TypeError(
                    f"BatchedNestedMap routes Map ops only, got {inner!r}"
                )
        elif isinstance(op, MapRm):
            clock = clock_lanes(op.clock, self.actors, na)
            mask = np.zeros((nk1,), bool)
            for k1 in op.keyset:
                mask[self.keys1.bounded_intern(k1, nk1, "outer key")] = True
            row, overflow = nested_ops.apply_key1_rm(
                row, jnp.asarray(clock), jnp.asarray(mask)
            )
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: outer deferred buffer full "
                    f"(cap {self.state.odvalid.shape[-1]})"
                )
        else:
            raise TypeError(f"not a Map op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    # ---- state path (CvRDT) -------------------------------------------
    def _check_flags(self, flags, what: str) -> None:
        sibling, inner, outer = (bool(x) for x in flags)
        if sibling:
            raise SlotOverflow(
                f"{what}: sibling slab full — rebuild with a larger sibling_cap"
            )
        if inner or outer:
            raise DeferredOverflow(
                f"{what}: {'inner' if inner else 'outer'} deferred buffer "
                f"full — rebuild with a larger deferred_cap"
            )

    def merge_from(self, dst: int, src: int) -> None:
        metrics.count("nested_map.merges")
        joined, flags = nested_ops.join(
            self._row(self.state, dst), self._row(self.state, src)
        )
        self._check_flags(flags, f"merge {src}->{dst}")
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, joined
        )

    def fold(self) -> Map:
        """Full-mesh anti-entropy: join all replicas, return the converged
        oracle-form state."""
        metrics.count("nested_map.merges", max(self.n_replicas - 1, 0))
        observe_depth("nested_map", self.state)
        folded, flags = nested_ops.fold(self.state)
        self._check_flags(flags, "fold")
        tmp = BatchedNestedMap(
            1, self.n_keys1, self.n_keys2,
            self.state.m.top.shape[-1],
            self.state.m.child.wact.shape[-1],
            self.state.odcl.shape[-2],
            keys1=self.keys1, keys2=self.keys2,
            actors=self.actors, values=self.values,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)
