"""BatchedList — N device-resident List replicas over a shared
identifier universe.

Oracle: ``crdt_tpu.pure.list.List`` (reference: src/list.rs). The split
per SURVEY.md §7.1: identifier allocation is inherently sequential per
edit trace and runs in the native host engine
(``crdt_tpu.native.ListEngine``, C++); the per-replica op application is
batched on device as masked scatters over an order-maintenance array.

Layout: the engine's total identifier order (which is immutable — dense
identifiers never move) assigns every identifier a static *slot*; the
device holds ``vals int32[R, N]`` + ``alive bool[R, N]`` in slot order.
Applying an insert is ``alive[slot] = True, vals[slot] = v``; a delete is
``alive[slot] = False``; a read is a host-side compress of ``vals`` by
``alive`` (already in sequence order). Epochs of ops across all replicas
land as one scatter each — the batched form of BASELINE config 5's
"100k ops × 1k replicas".
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dot import OrdDot
from ..native import DELETE, INSERT, ListEngine
from ..pure.identifier import Identifier
from ..pure.list import List


def growth_permutation(old_slots: np.ndarray, new_rank: np.ndarray) -> np.ndarray:
    """After the engine mints more identifiers, map the new total order
    back onto the old one: ``src[s]`` is the old slot feeding new slot
    ``s`` (-1 = freshly minted). Handles are stable and ordered
    first-n-minted-first, so the old handles are ``new_rank[:n_old]``."""
    src = np.full(len(new_rank), -1, np.int64)
    src[new_rank[: len(old_slots)]] = old_slots
    return src


class BatchedList:
    def __init__(self, n_replicas: int):
        self.engine = ListEngine()
        # rank per identifier handle (the current total order)
        self.slots = np.empty(0, np.int64)
        self.vals = jnp.zeros((n_replicas, 1), jnp.int32)
        self.alive = jnp.zeros((n_replicas, 1), bool)
        self._mesh = None  # set by place(): (replica, element) sharding
        # The op log: stable identifier handles (slots move when later
        # inserts interleave the order; handles never do).
        self.op_handles = np.empty(0, np.int64)
        self.op_kinds = np.empty(0, np.uint8)
        self.op_vals = np.empty(0, np.int32)
        self._applied = 0  # watermark: ops [0, _applied) are on device

    def place(self, mesh) -> None:
        """Shard the replica state over a ``(replica, element)`` mesh:
        replicas data-parallel, the slot universe sharded over the
        element axis (the sequence-parallel analog, SURVEY.md §3.1 —
        identifier space across devices). Epoch scatters carry
        replicated indices and XLA partitions them; streamed universe
        growth re-places after every slot re-permutation.

        Placement is per-session: it is not persisted by
        ``crdt_tpu.checkpoint`` (a mesh names live devices) — re-call
        ``place`` on a restored model."""
        from ..parallel.mesh import REPLICA_AXIS

        # Validate BEFORE installing: a rejected place() must leave the
        # model untouched (an installed mesh would make the next
        # extend_trace mutate the engine and then fail mid-operation).
        rmult = mesh.shape[REPLICA_AXIS]
        if self.vals.shape[0] % rmult:
            raise ValueError(
                f"{self.vals.shape[0]} replicas do not divide the "
                f"{rmult}-way replica mesh axis"
            )
        self._mesh = mesh
        self.vals, self.alive = self._placed(self.vals, self.alive)

    def _placed(self, vals, alive):
        if self._mesh is None:
            return vals, alive
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import ELEMENT_AXIS, REPLICA_AXIS

        mesh = self._mesh
        pad_n = (-vals.shape[1]) % mesh.shape[ELEMENT_AXIS]
        if pad_n:
            # Dead slot padding: never addressed (scatters drop at the
            # out-of-range lane, reads mask on alive).
            vals = jnp.pad(vals, ((0, 0), (0, pad_n)))
            alive = jnp.pad(alive, ((0, 0), (0, pad_n)))
        spec = NamedSharding(mesh, P(REPLICA_AXIS, ELEMENT_AXIS))
        return jax.device_put(vals, spec), jax.device_put(alive, spec)

    @classmethod
    def from_trace(
        cls,
        kinds: Sequence[int],
        indices: Sequence[int],
        values: Sequence[int],
        actors: Sequence[int],
        n_replicas: int,
    ) -> "BatchedList":
        """Build the shared identifier universe by running the edit trace
        through the native engine, then stand up ``n_replicas`` empty
        device replicas over it. For streamed ingestion start from
        ``BatchedList(n_replicas)`` and call :meth:`extend_trace` per
        chunk instead."""
        out = cls(n_replicas)
        out.extend_trace(kinds, indices, values, actors)
        return out

    def extend_trace(
        self,
        kinds: Sequence[int],
        indices: Sequence[int],
        values: Sequence[int],
        actors: Sequence[int],
    ) -> None:
        """Grow the shared identifier universe with further local edit
        ops (streamed ingestion — the trace need not be known up front,
        SURVEY.md §4.5 / BASELINE config 5). New identifiers may
        interleave existing ones, so device slots are re-permuted to the
        new total order; applied state moves with its identifiers."""
        handles = self.engine.apply_trace(kinds, indices, values, actors)
        new_rank = self.engine.total_order()
        if len(new_rank) != len(self.slots):
            src = growth_permutation(self.slots, new_rank)
            self.vals, self.alive = self._placed(
                *_remap_slots(self.vals, self.alive, jnp.asarray(src))
            )
            self.slots = new_rank
        self.op_handles = np.concatenate([self.op_handles, handles])
        self.op_kinds = np.concatenate(
            [self.op_kinds, np.ascontiguousarray(kinds, np.uint8)]
        )
        self.op_vals = np.concatenate(
            [self.op_vals, np.ascontiguousarray(values, np.int32)]
        )

    @property
    def op_slots(self) -> np.ndarray:
        """Current slot of every logged op (recomputed: slots move as the
        universe grows, handles don't)."""
        return self.slots[self.op_handles]

    @property
    def n_replicas(self) -> int:
        return self.vals.shape[0]

    # ---- batched op application (the device hot path) -----------------
    def apply_ops(self, replica_ops: np.ndarray, op_slots: Optional[np.ndarray] = None) -> None:
        """One epoch: ``replica_ops[r]`` lists trace-op indices for
        replica ``r`` (shape [R, C]; -1 pads). Within one epoch a
        replica must not touch the same slot twice (scatter order on
        duplicates is unspecified) — chunk the trace accordingly.
        The whole epoch is two scatters for ALL replicas.

        ``op_slots`` lets loop callers pass the op→slot table computed
        once (it is an O(oplog) gather otherwise)."""
        replica_ops = np.asarray(replica_ops)
        if replica_ops.ndim != 2 or replica_ops.shape[0] != self.n_replicas:
            raise ValueError(f"expected [R={self.n_replicas}, C] op indices")
        from ..config import config

        if config.strict:
            # The device analog of pure.list.List.validate_op's dup
            # rejection: a trace-op index delivered twice to one replica
            # in one epoch is a duplicate dot (the engine mints each op's
            # dot once), and scatter order on duplicates is unspecified.
            from ..traits import DotRange

            for r in range(replica_ops.shape[0]):
                live = replica_ops[r][replica_ops[r] >= 0]
                uniq, counts = np.unique(live, return_counts=True)
                if (counts > 1).any():
                    dup = int(uniq[counts > 1][0])
                    raise DotRange(f"replica {r} trace op", dup, dup)
        if op_slots is None:
            op_slots = self.op_slots
        valid = replica_ops >= 0
        safe = np.where(valid, replica_ops, 0)
        # Pad lanes scatter to the out-of-range slot N and are dropped —
        # routing them to slot 0 would duplicate-write a real slot with
        # an unspecified winner.
        n = self.vals.shape[1]
        slots = jnp.asarray(np.where(valid, op_slots[safe], n))
        kinds = jnp.asarray(self.op_kinds[safe])
        vals = jnp.asarray(self.op_vals[safe])
        self.vals, self.alive = _apply_epoch(
            self.vals, self.alive, slots, kinds, vals, jnp.asarray(valid)
        )

    def apply_trace_to_all(self, chunk: int = 4096) -> None:
        """Apply the not-yet-applied tail of the op log to every replica
        in fixed-size epochs (streamed calls pick up where the last one
        stopped). Within an epoch, ops on the same slot compose to the
        LAST one (a slot's lifecycle is insert → delete, so the final
        write wins exactly) — the host dedupes, and each epoch lands as
        one conflict-free scatter for all replicas."""
        n_ops = len(self.op_handles)
        op_slots = self.op_slots  # one gather; slots are stable herein
        for start in range(self._applied, n_ops, chunk):
            ep = np.arange(start, min(start + chunk, n_ops))
            # keep the last op per slot: first occurrence in the reversed
            # window is the last in trace order
            rev = ep[::-1]
            _, first = np.unique(op_slots[rev], return_index=True)
            keep = rev[first]
            # Pad to the fixed chunk width (-1 lanes are dropped) so every
            # epoch shares one traced shape — a data-dependent width would
            # recompile _apply_epoch per epoch.
            padded = np.full(chunk, -1, np.int64)
            padded[: len(keep)] = keep
            ops = np.broadcast_to(padded, (self.n_replicas, chunk))
            self.apply_ops(ops, op_slots=op_slots)
        self._applied = n_ops

    # ---- cross-process op exchange (SURVEY §4.5: the reference ships
    # ``Op::Insert { id, val }`` bytes to ANY replica; the TPU build's
    # multi-host analog ships identifier paths over DCN) ----------------
    def export_ops(self, start: int = 0, end: Optional[int] = None):
        """Flatten ops ``[start, end)`` of the local log to plain numpy
        arrays (kind, value, path length, flattened (index, actor,
        counter) components) — the wire form for
        ``parallel.multihost.sync_list``. Identifier paths are globally
        unique and totally ordered by construction, so a remote engine
        ingesting them reproduces the same total order."""
        end = len(self.op_handles) if end is None else end
        kinds = self.op_kinds[start:end]
        values = self.op_vals[start:end]
        paths = [
            self.engine.identifier_path(int(h))
            for h in self.op_handles[start:end]
        ]
        counts = np.asarray([len(p) for p in paths], np.int64)
        flat = [c for p in paths for c in p]
        return {
            "kinds": np.ascontiguousarray(kinds, np.uint8),
            "values": np.ascontiguousarray(values, np.int32),
            "counts": counts,
            "cidx": np.asarray([c[0] for c in flat], np.int64),
            "cactor": np.asarray([c[1] for c in flat], np.int32),
            "cctr": np.asarray([c[2] for c in flat], np.uint64),
        }

    def ingest_remote_ops(self, wire) -> None:
        """Apply a remote process's exported ops into the local engine
        (idempotent: duplicate identifiers no-op) and append them to the
        op log; device slots re-permute to the grown total order."""
        counts = wire["counts"]
        if len(counts) == 0:
            return
        offsets = np.concatenate([[0], np.cumsum(counts)])
        paths = [
            [
                (
                    int(wire["cidx"][i]),
                    int(wire["cactor"][i]),
                    int(wire["cctr"][i]),
                )
                for i in range(offsets[j], offsets[j + 1])
            ]
            for j in range(len(counts))
        ]
        handles = self.engine.apply_remote(
            wire["kinds"], paths, wire["values"]
        )
        new_rank = self.engine.total_order()
        if len(new_rank) != len(self.slots):
            src = growth_permutation(self.slots, new_rank)
            self.vals, self.alive = self._placed(
                *_remap_slots(self.vals, self.alive, jnp.asarray(src))
            )
            self.slots = new_rank
        # A delete of an identifier the engine never saw is an idempotent
        # no-op and yields handle -1 — it must NOT enter the op log
        # (self.slots[-1] would wrap to the highest-ranked identifier and
        # the scatter would clear an unrelated element).
        ok = handles >= 0
        self.op_handles = np.concatenate([self.op_handles, handles[ok]])
        self.op_kinds = np.concatenate(
            [self.op_kinds, np.ascontiguousarray(wire["kinds"], np.uint8)[ok]]
        )
        self.op_vals = np.concatenate(
            [self.op_vals, np.ascontiguousarray(wire["values"], np.int32)[ok]]
        )

    # ---- reads ---------------------------------------------------------
    def read(self, replica: int) -> list:
        """The replica's sequence of value ids (slot order == identifier
        order)."""
        alive = np.asarray(self.alive[replica])
        vals = np.asarray(self.vals[replica])
        return vals[alive].tolist()

    def to_pure(self, replica: int, actors_table=None) -> List:
        """Reconstruct the oracle form (identifiers from the engine,
        values from device state). ``actors_table`` maps dense actor ids
        back to caller actors (identity if omitted)."""
        alive = np.asarray(self.alive[replica])
        vals = np.asarray(self.vals[replica])
        out = List()
        handle_of_slot = np.argsort(self.slots, kind="stable")
        for slot in range(len(self.slots)):
            if not alive[slot]:
                continue
            handle = int(handle_of_slot[slot])
            path = self.engine.identifier_path(handle)
            ident = Identifier(
                tuple(
                    (
                        ix,
                        OrdDot(
                            actors_table[a] if actors_table is not None else a,
                            c,
                        ),
                    )
                    for ix, a, c in path
                )
            )
            out.seq.append(ident)
            out.vals[ident] = int(vals[slot])
        return out


@jax.jit
def _remap_slots(vals, alive, src):
    """Permute replica state to a new total order: ``src[s]`` is the old
    slot feeding new slot ``s`` (-1 = freshly minted identifier, empty
    on every replica)."""
    safe = jnp.where(src >= 0, src, 0)
    fresh = src[None, :] < 0
    return (
        jnp.where(fresh, 0, vals[:, safe]),
        jnp.where(fresh, False, alive[:, safe]),
    )


@jax.jit
def _apply_epoch(vals, alive, slots, kinds, epoch_vals, valid):
    """Scatter one epoch of ops into all replicas: inserts set value +
    alive, deletes clear alive. [R, C] everywhere."""
    r = jnp.arange(vals.shape[0])[:, None]
    insert = valid & (kinds == INSERT)
    delete = valid & (kinds == DELETE)
    vals = vals.at[r, slots].set(
        jnp.where(insert, epoch_vals, vals[r, slots]), mode="drop"
    )
    new_alive = jnp.where(
        insert, True, jnp.where(delete, False, alive[r, slots])
    )
    alive = alive.at[r, slots].set(new_alive, mode="drop")
    return vals, alive
