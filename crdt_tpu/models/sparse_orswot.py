"""BatchedSparseOrswot — N segment-encoded ORSWOT replicas on device.

The sparse counterpart of :class:`.orswot.BatchedOrswot` for element
universes where the dense ``ctr[R, E, A]`` cube stops scaling (SURVEY.md
§7.3): state size tracks LIVE (member, actor) cells, not the universe.
Members are interned exactly as in the dense model — the member
universe may be unboundedly large; only ``dot_cap`` bounds the live
cells per replica. Conversion to/from the oracle is lossless (including
parked removes, bounded by ``rm_width`` elements per parked clock), and
never materializes a dense cube.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import sparse_orswot as ops
from ..pure.orswot import Add, Orswot, Rm
from ..utils import Interner, clock_lanes, transactional_apply
from ..utils.metrics import metrics, observe_depth
from ..vclock import VClock
from .orswot import DeferredOverflow


class DotCapacityOverflow(RuntimeError):
    """A replica's live cells exceeded ``dot_cap`` — rebuild the model
    with a larger capacity (sparse mode bounds live dots, not the
    universe)."""


class BatchedSparseOrswot:
    def __init__(
        self,
        n_replicas: int,
        dot_cap: int,
        n_actors: int,
        deferred_cap: int = 4,
        rm_width: int = 8,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
    ):
        self.members = members if members is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.state = ops.empty(
            dot_cap, n_actors, deferred_cap, rm_width, batch=(n_replicas,)
        )

    @property
    def n_replicas(self) -> int:
        return self.state.top.shape[0]

    @property
    def dot_cap(self) -> int:
        return self.state.eid.shape[-1]

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Orswot],
        dot_cap: int = 256,
        deferred_cap: int = 4,
        rm_width: int = 8,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        n_actors: int = 1,
    ) -> "BatchedSparseOrswot":
        members = members if members is not None else Interner()
        actors = actors if actors is not None else Interner()
        for p in pures:
            for a in p.clock.dots:
                actors.intern(a)
            for m, clock in p.entries.items():
                members.intern(m)
                for a in clock.dots:
                    actors.intern(a)
            for clock, ms in p.deferred.items():
                for a in clock.dots:
                    actors.intern(a)
                for m in ms:
                    members.intern(m)

        r = len(pures)
        na = max(len(actors), n_actors, 1)
        out = cls(
            r, dot_cap, na, deferred_cap, rm_width,
            members=members, actors=actors,
        )
        top = np.zeros((r, na), np.uint32)
        eid = np.full((r, dot_cap), -1, np.int32)
        act = np.zeros((r, dot_cap), np.int32)
        ctr = np.zeros((r, dot_cap), np.uint32)
        valid = np.zeros((r, dot_cap), bool)
        dcl = np.zeros((r, deferred_cap, na), np.uint32)
        didx = np.full((r, deferred_cap, rm_width), -1, np.int32)
        dvalid = np.zeros((r, deferred_cap), bool)
        for i, p in enumerate(pures):
            for a, c in p.clock.dots.items():
                top[i, actors.id_of(a)] = c
            cells = sorted(
                (members.id_of(m), actors.id_of(a), c)
                for m, clock in p.entries.items()
                for a, c in clock.dots.items()
            )
            if len(cells) > dot_cap:
                raise DotCapacityOverflow(
                    f"replica {i}: {len(cells)} live cells > dot_cap {dot_cap}"
                )
            for s, (e, a, c) in enumerate(cells):
                eid[i, s], act[i, s], ctr[i, s], valid[i, s] = e, a, c, True
            if len(p.deferred) > deferred_cap:
                raise DeferredOverflow(
                    f"replica {i}: {len(p.deferred)} parked removes; "
                    f"capacity is {deferred_cap}"
                )
            for s, (clock, ms) in enumerate(p.deferred.items()):
                ids = sorted(members.id_of(m) for m in ms)
                if len(ids) > rm_width:
                    raise DeferredOverflow(
                        f"replica {i} slot {s}: {len(ids)} parked elements "
                        f"> rm_width {rm_width}"
                    )
                for a, c in clock.dots.items():
                    dcl[i, s, actors.id_of(a)] = c
                didx[i, s, : len(ids)] = ids
                dvalid[i, s] = True
        out.state = ops.SparseOrswotState(
            top=jnp.asarray(top), eid=jnp.asarray(eid), act=jnp.asarray(act),
            ctr=jnp.asarray(ctr), valid=jnp.asarray(valid),
            dcl=jnp.asarray(dcl), didx=jnp.asarray(didx),
            dvalid=jnp.asarray(dvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Orswot:
        st = jax.device_get(self._row(self.state, i))
        out = Orswot()
        out.clock = VClock(
            {self.actors[a]: int(c) for a, c in enumerate(st.top) if c > 0}
        )
        for s in np.nonzero(st.valid)[0]:
            m = self.members[int(st.eid[s])]
            entry = out.entries.setdefault(m, VClock())
            entry.dots[self.actors[int(st.act[s])]] = int(st.ctr[s])
        for s in np.nonzero(st.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.dcl[s]) if c > 0}
            )
            # Equal-clock slots union into ONE oracle entry (the sparse
            # form legitimately splits a clock's list across slots when
            # the union exceeds rm_width — the oracle's dict cannot).
            out.deferred.setdefault(clock, set()).update(
                self.members[int(e)] for e in st.didx[s] if e >= 0
            )
        return out

    # ---- op path (CmRDT) ----------------------------------------------
    def _eids(self, members_iter, width: Optional[int] = None) -> np.ndarray:
        """Intern the op's members into a fixed-width id list. ``width``
        None sizes by the op (rounded up to a power-of-two bucket ≥ 8 to
        bound jit retraces); the rm path passes ``rm_width`` because a
        parked list must fit its buffer lane."""
        ids = [self.members.intern(m) for m in members_iter]
        if width is None:
            width = 8
            while width < len(ids):
                width *= 2
        if len(ids) > width:
            # DeferredOverflow (not ValueError): a too-narrow parked
            # lane is capacity pressure, and elastic.axes_for implicates
            # rm_width so the recovery loop can widen it and retry.
            raise DeferredOverflow(
                f"op lists {len(ids)} members; rm_width is {width} — "
                f"rebuild with a larger rm_width or split the op"
            )
        out = np.full(width, -1, np.int32)
        out[: len(ids)] = ids
        return out

    @transactional_apply("members", "actors")
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/orswot.rs ``CmRDT::apply``)."""
        from .validation import strict_validate_dot

        row = self._row(self.state, replica)
        na = self.state.top.shape[-1]
        if isinstance(op, Add):
            strict_validate_dot(row.top, self.actors, op.dot.actor, op.dot.counter)
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            row, overflow = ops.apply_add(
                row,
                jnp.asarray(aid),
                jnp.asarray(np.uint32(op.dot.counter)),
                jnp.asarray(self._eids(op.members)),
            )
            if bool(overflow):
                raise DotCapacityOverflow(
                    f"replica {replica}: dot_cap {self.dot_cap} exceeded"
                )
        elif isinstance(op, Rm):
            clock = clock_lanes(
                op.clock, self.actors, na, dtype=self.state.top.dtype
            )
            row, overflow = ops.apply_rm(
                row,
                jnp.asarray(clock),
                jnp.asarray(
                    self._eids(op.members, width=self.state.didx.shape[-1])
                ),
            )
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: deferred buffer full "
                    f"(cap {self.state.dvalid.shape[-1]})"
                )
        else:
            raise TypeError(f"not an Orswot op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    # ---- state path (CvRDT) -------------------------------------------
    def _check(self, flags, what: str) -> None:
        if bool(flags[0]):
            raise DotCapacityOverflow(
                f"{what}: survivor cells exceed dot_cap {self.dot_cap}"
            )
        if bool(flags[1]):
            raise DeferredOverflow(f"{what}: deferred buffer full")

    @transactional_apply("actors")
    def reset_remove(self, replica: int, clock) -> None:
        """``Causal::reset_remove`` on one replica: forget all causal
        history the given ``VClock`` dominates (reference: src/orswot.rs
        ResetRemove impl; oracle: pure/orswot.py; dense sibling:
        BatchedOrswot.reset_remove)."""
        cl = clock_lanes(
            clock, self.actors, self.state.top.shape[-1],
            dtype=self.state.top.dtype,
        )
        row = ops.reset_remove(self._row(self.state, replica), jnp.asarray(cl))
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    def merge_from(self, dst: int, src: int) -> None:
        # No per-merge span: hot path — spans live at fold granularity.
        metrics.count("sparse_orswot.merges")
        joined, flags = ops.join(
            self._row(self.state, dst), self._row(self.state, src)
        )
        self._check(flags, f"merge {src}->{dst}")
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, joined
        )

    def fold(self) -> Orswot:
        """Full-mesh anti-entropy: join all replicas, return the
        converged oracle-form state."""
        from ..telemetry import span

        metrics.count("sparse_orswot.merges", max(self.n_replicas - 1, 0))
        observe_depth("sparse_orswot", self.state)
        with span("model.sparse_orswot.fold", replicas=self.n_replicas):
            folded, flags = ops.fold(self.state)
        self._check(flags, "fold")
        tmp = BatchedSparseOrswot(
            1, self.dot_cap, self.state.top.shape[-1],
            self.state.dcl.shape[-2], self.state.didx.shape[-1],
            members=self.members, actors=self.actors,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)

    def members_of(self, i: int) -> frozenset:
        st = jax.device_get(self._row(self.state, i))
        return frozenset(
            self.members[int(e)]
            for e in np.unique(np.asarray(st.eid)[np.asarray(st.valid)])
        )

    # ---- elastic capacity migration (elastic.py) ----------------------
    def widen_capacity(
        self,
        dot_cap: int = 0,
        n_actors: int = 0,
        deferred_cap: int = 0,
        rm_width: int = 0,
    ) -> None:
        """Segment-table repack into a wider layout in place — the
        sanctioned recovery from ``DotCapacityOverflow`` /
        ``DeferredOverflow`` (elastic.py drives this; the migration is
        ``ops.sparse_orswot.widen``). 0 keeps a width; interners and ids
        are untouched and the result is bit-identical to a from-scratch
        model built at the wider capacity holding the same state."""
        self.state = ops.widen(
            self.state, dot_cap, n_actors, deferred_cap, rm_width
        )

    def narrow_capacity(
        self,
        dot_cap: int = 0,
        n_actors: int = 0,
        deferred_cap: int = 0,
        rm_width: int = 0,
    ) -> None:
        """The inverse migration — slice the segment table down in
        place (elastic.shrink drives this under the hysteresis policy).
        Refuses when occupancy does not fit (``ops.sparse_orswot.narrow``
        checks the device planes; the actor check also covers the
        interner — actor ids are lane ids). 0 keeps a width."""
        if n_actors and n_actors < len(self.actors):
            raise ValueError(
                f"narrow refused: {len(self.actors)} actors interned > "
                f"target n_actors {n_actors}"
            )
        self.state = ops.narrow(
            self.state, dot_cap, n_actors, deferred_cap, rm_width
        )
