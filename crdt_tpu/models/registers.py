"""Batched register models — LWWReg (max-marker select) and MVReg
(sibling slots) on device.

Oracles: ``crdt_tpu.pure.lwwreg.LWWReg`` (reference: src/lwwreg.rs) and
``crdt_tpu.pure.mvreg.MVReg`` (reference: src/mvreg.rs). Device constraint
(documented deviation): LWW markers must be integers in [0, 2^64) —
the two-u32-lane device encoding; the pure oracle keeps the reference's
full ``M: Ord`` genericity. Values of both registers are interned to
dense ids (host table, like actors/members everywhere else).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dot import Dot
from ..ops import lwwreg as lww_ops
from ..ops import mvreg as mv_ops
from ..pure.lwwreg import UNSET, LWWReg
from ..pure.mvreg import MVReg, Put
from ..traits import ConflictingMarker
from ..utils import Interner, clock_lanes, transactional_apply
from ..vclock import VClock


class SlotOverflow(RuntimeError):
    """A sibling could not be held: the slot buffer exceeded its static
    capacity. Raise rather than silently dropping concurrent writes —
    rebuild the model with a larger ``n_slots``."""


def _split_marker(marker: int):
    if not isinstance(marker, int) or not (0 <= marker < 2**64):
        raise TypeError(
            f"device LWW markers must be ints in [0, 2**64), got {marker!r}"
        )
    return marker >> 32, marker & 0xFFFFFFFF


class BatchedLWWReg:
    def __init__(self, n_replicas: int, values: Optional[Interner] = None):
        self.values = values if values is not None else Interner()
        self.state = lww_ops.empty(batch=(n_replicas,))

    @property
    def n_replicas(self) -> int:
        return self.state.hi.shape[0]

    @classmethod
    def from_pure(cls, pures: Sequence[LWWReg], values: Optional[Interner] = None) -> "BatchedLWWReg":
        values = values if values is not None else Interner()
        hi = np.zeros(len(pures), np.uint32)
        lo = np.zeros(len(pures), np.uint32)
        val = np.zeros(len(pures), np.int32)
        has = np.zeros(len(pures), bool)
        for i, p in enumerate(pures):
            if p.val is UNSET:
                continue
            h, l = _split_marker(p.marker)
            hi[i], lo[i] = h, l
            val[i] = values.intern(p.val)
            has[i] = True
        out = cls(len(pures), values=values)
        out.state = lww_ops.LWWState(
            hi=jnp.asarray(hi), lo=jnp.asarray(lo), val=jnp.asarray(val), has=jnp.asarray(has)
        )
        return out

    def to_pure(self, i: int) -> LWWReg:
        if not bool(self.state.has[i]):
            return LWWReg()
        marker = (int(self.state.hi[i]) << 32) | int(self.state.lo[i])
        return LWWReg(self.values[int(self.state.val[i])], marker)

    @transactional_apply("values")
    def update(self, replica: int, val, marker: int) -> None:
        """Reference: src/lwwreg.rs ``update`` + validation."""
        h, l = _split_marker(marker)
        row = jax.tree.map(lambda x: x[replica], self.state)
        row, conflict = lww_ops.apply_update(
            row, jnp.asarray(h, jnp.uint32), jnp.asarray(l, jnp.uint32),
            jnp.asarray(self.values.intern(val), jnp.int32),
        )
        if bool(conflict):
            raise ConflictingMarker(
                f"replica {replica}: marker {marker!r} already guards a different value"
            )
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    def merge_from(self, dst: int, src: int) -> None:
        row, conflict = lww_ops.join(
            jax.tree.map(lambda x: x[dst], self.state),
            jax.tree.map(lambda x: x[src], self.state),
        )
        if bool(conflict):
            raise ConflictingMarker(f"merge {src}->{dst}: equal markers, different values")
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, row
        )

    def fold(self) -> LWWReg:
        folded, conflict = lww_ops.fold(self.state)
        if bool(conflict):
            raise ConflictingMarker("fold: equal markers guard different values")
        tmp = BatchedLWWReg(1, values=self.values)
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)


class BatchedMVReg:
    def __init__(
        self,
        n_replicas: int,
        n_actors: int,
        n_slots: int = 8,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
    ):
        self.actors = actors if actors is not None else Interner()
        self.values = values if values is not None else Interner()
        self.state = mv_ops.empty(n_slots, n_actors, batch=(n_replicas,))

    @property
    def n_replicas(self) -> int:
        return self.state.wact.shape[0]

    @classmethod
    def from_pure(
        cls,
        pures: Sequence[MVReg],
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
        n_slots: int = 8,
        n_actors: int = 0,
    ) -> "BatchedMVReg":
        """``n_actors`` sets a capacity FLOOR above the actors present
        in ``pures`` — spare lanes later ops intern into."""
        actors = actors if actors is not None else Interner()
        values = values if values is not None else Interner()
        for p in pures:
            for dot, (clock, v) in p.vals.items():
                actors.intern(dot.actor)
                for a in clock.dots:
                    actors.intern(a)
                values.intern(v)

        r, a = len(pures), max(len(actors), n_actors, 1)
        out = cls(r, a, n_slots=n_slots, actors=actors, values=values)
        wact = np.zeros((r, n_slots), np.int32)
        wctr = np.zeros((r, n_slots), np.uint32)
        clk = np.zeros((r, n_slots, a), np.uint32)
        val = np.zeros((r, n_slots), np.int32)
        valid = np.zeros((r, n_slots), bool)
        for i, p in enumerate(pures):
            if len(p.vals) > n_slots:
                raise ValueError(
                    f"replica {i} has {len(p.vals)} siblings; capacity is {n_slots}"
                )
            for s, (dot, (clock, v)) in enumerate(p.vals.items()):
                wact[i, s] = actors.id_of(dot.actor)
                wctr[i, s] = dot.counter
                for actor, c in clock.dots.items():
                    clk[i, s, actors.id_of(actor)] = c
                val[i, s] = values.id_of(v)
                valid[i, s] = True
        out.state = mv_ops.MVRegState(
            wact=jnp.asarray(wact), wctr=jnp.asarray(wctr), clk=jnp.asarray(clk),
            val=jnp.asarray(val), valid=jnp.asarray(valid),
        )
        return out

    def to_pure(self, i: int) -> MVReg:
        st = jax.device_get(jax.tree.map(lambda x: x[i], self.state))
        out = MVReg()
        for s in np.nonzero(st.valid)[0]:
            dot = Dot(self.actors[int(st.wact[s])], int(st.wctr[s]))
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.clk[s]) if c > 0}
            )
            out.vals[dot] = (clock, self.values[int(st.val[s])])
        return out

    @transactional_apply("actors", "values")
    def apply(self, replica: int, op: Put) -> None:
        """Apply an oracle-shaped Put to one replica (reference:
        src/mvreg.rs ``CmRDT::apply``). Under ``config.strict`` the
        Put's witness dot must be the minter's next contiguous event
        against the replica's observed clock (the join of its live
        content clocks — MVReg stores no top), mirroring
        ``pure.mvreg.MVReg.validate_op``. Validation runs FIRST (before
        actor-lane allocation) so a rejected op is side-effect free and
        never-seen actors get DotRange, not KeyError."""
        from ..config import config

        if config.strict:
            from .validation import strict_validate_dot

            row_clk = jnp.max(
                jnp.where(
                    self.state.valid[replica][..., None],
                    self.state.clk[replica],
                    0,
                ),
                axis=-2,
            )
            strict_validate_dot(
                row_clk, self.actors, op.dot.actor, op.dot.counter
            )
        a = self.state.clk.shape[-1]
        aid = self.actors.bounded_intern(op.dot.actor, a, "actor")
        cl = clock_lanes(op.clock, self.actors, a)
        row = jax.tree.map(lambda x: x[replica], self.state)
        row, overflow = mv_ops.apply_put(
            row,
            jnp.asarray(aid, jnp.int32),
            jnp.asarray(op.dot.counter, jnp.uint32),
            jnp.asarray(cl),
            jnp.asarray(self.values.intern(op.val), jnp.int32),
        )
        if bool(overflow):
            raise SlotOverflow(
                f"replica {replica}: sibling slots full (cap {self.state.valid.shape[-1]})"
            )
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    @transactional_apply("actors")
    def reset_remove(self, replica: int, clock) -> None:
        """``Causal::reset_remove`` on one replica: forget siblings whose
        full write clock the given ``VClock`` dominates (reference:
        src/mvreg.rs ResetRemove impl; oracle: pure/mvreg.py)."""
        cl = clock_lanes(clock, self.actors, self.state.clk.shape[-1])
        row = mv_ops.reset_remove(
            jax.tree.map(lambda x: x[replica], self.state), jnp.asarray(cl)
        )
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    def merge_from(self, dst: int, src: int) -> None:
        row, overflow = mv_ops.join(
            jax.tree.map(lambda x: x[dst], self.state),
            jax.tree.map(lambda x: x[src], self.state),
        )
        if bool(overflow):
            raise SlotOverflow(
                f"merge {src}->{dst}: sibling slots full (cap {self.state.valid.shape[-1]})"
            )
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, row
        )

    def fold(self) -> MVReg:
        folded, overflow = mv_ops.fold(self.state)
        if bool(overflow):
            raise SlotOverflow(
                f"fold: sibling slots full (cap {self.state.valid.shape[-1]})"
            )
        tmp = BatchedMVReg(
            1, self.state.clk.shape[-1], self.state.valid.shape[-1],
            actors=self.actors, values=self.values,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)
