"""BatchedSparseMap — N segment-encoded ``Map<K, MVReg<V>>`` replicas.

The sparse sibling of ``BatchedMap`` (models/map.py): same oracle
(``crdt_tpu.pure.map.Map`` with MVReg children, reference src/map.rs at
the BASELINE config-4 shape), same op surface, same lossless
``to_pure``/``from_pure`` A/B boundary — but state proportional to LIVE
cells (``ops/sparse_mvmap.py``), so the key universe can be 100M+ ids
wide while a replica holds kilobytes. Conversion builds segments
directly from the oracle dicts (never materialising a dense slab), so
``from_pure`` scales with content, not with the universe.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dot import Dot
from ..ops import sparse_mvmap as ops
from ..pure.map import Map, MapRm, Nop, Up
from ..pure.mvreg import MVReg, Put
from ..utils import Interner, clock_lanes, transactional_apply
from ..utils.metrics import metrics, observe_depth
from ..vclock import VClock
from .orswot import DeferredOverflow
from .registers import SlotOverflow
from .sparse_orswot import DotCapacityOverflow
from .validation import strict_validate_dot


class BatchedSparseMap:
    def __init__(
        self,
        n_replicas: int,
        n_keys: int,
        n_actors: int,
        cell_cap: int = 64,
        sibling_cap: int = 4,
        deferred_cap: int = 4,
        rm_width: int = 8,
        keys: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
    ):
        if n_keys * n_actors > 2**31 - 1:
            raise ValueError(
                f"key universe too wide for the int32 packed-cell key: "
                f"n_keys * n_actors = {n_keys * n_actors:,} > 2^31-1 "
                f"(shrink n_keys or n_actors)"
            )
        self.keys = keys if keys is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.values = values if values is not None else Interner()
        self.n_keys = n_keys
        self.sibling_cap = sibling_cap
        self.state = ops.empty(
            cell_cap, n_actors, deferred_cap, rm_width, batch=(n_replicas,)
        )

    @property
    def n_replicas(self) -> int:
        return self.state.top.shape[0]

    @property
    def cell_cap(self) -> int:
        return self.state.kid.shape[-1]

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Map],
        keys: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
        cell_cap: int = 64,
        sibling_cap: int = 4,
        deferred_cap: int = 4,
        rm_width: int = 8,
        n_keys: int = 0,
        n_actors: int = 0,
    ) -> "BatchedSparseMap":
        """Build segments straight from the oracle dicts — cost is
        O(live cells), independent of the key universe. ``n_keys`` /
        ``n_actors`` set capacity FLOORS above the names present."""
        keys = keys if keys is not None else Interner()
        actors = actors if actors is not None else Interner()
        values = values if values is not None else Interner()
        for p in pures:
            for actor in p.clock.dots:
                actors.intern(actor)
            for k, child in p.entries.items():
                keys.intern(k)
                if not isinstance(child, MVReg):
                    raise TypeError(
                        f"BatchedSparseMap children must be MVReg, got "
                        f"{type(child)}"
                    )
                for d, (clock, v) in child.vals.items():
                    actors.intern(d.actor)
                    for actor in clock.dots:
                        actors.intern(actor)
                    values.intern(v)
            for clock, ks in p.deferred.items():
                for actor in clock.dots:
                    actors.intern(actor)
                for k in ks:
                    keys.intern(k)

        r = len(pures)
        na = max(len(actors), n_actors, 1)
        out = cls(
            r, max(len(keys), n_keys, 1), na, cell_cap, sibling_cap,
            deferred_cap, rm_width, keys=keys, actors=actors, values=values,
        )
        d = deferred_cap
        top = np.zeros((r, na), np.uint32)
        kid = np.full((r, cell_cap), -1, np.int32)
        act = np.zeros((r, cell_cap), np.int32)
        ctr = np.zeros((r, cell_cap), np.uint32)
        val = np.zeros((r, cell_cap), np.int32)
        clk = np.zeros((r, cell_cap, na), np.uint32)
        valid = np.zeros((r, cell_cap), bool)
        dcl = np.zeros((r, d, na), np.uint32)
        kidx = np.full((r, d, rm_width), -1, np.int32)
        dvalid = np.zeros((r, d), bool)
        for i, p in enumerate(pures):
            for actor, c in p.clock.dots.items():
                top[i, actors.id_of(actor)] = c
            cells = []
            for k, child in p.entries.items():
                for dd, (clock, v) in child.vals.items():
                    cells.append((keys.id_of(k), actors.id_of(dd.actor),
                                  dd.counter, clock, v))
            if len(cells) > cell_cap:
                raise DotCapacityOverflow(
                    f"replica {i}: {len(cells)} live cells > cap {cell_cap}"
                )
            for s, (ki, ai, c, clock, v) in enumerate(
                sorted(cells, key=lambda t: (t[0], t[1]))
            ):
                kid[i, s], act[i, s], ctr[i, s] = ki, ai, c
                val[i, s] = values.id_of(v)
                for actor, cc in clock.dots.items():
                    clk[i, s, actors.id_of(actor)] = cc
                valid[i, s] = True
            if len(p.deferred) > deferred_cap:
                raise DeferredOverflow(
                    f"replica {i}: {len(p.deferred)} parked removes > "
                    f"cap {deferred_cap}"
                )
            for s, (clock, ks) in enumerate(p.deferred.items()):
                for actor, cc in clock.dots.items():
                    dcl[i, s, actors.id_of(actor)] = cc
                ids = sorted(keys.id_of(k) for k in ks)
                if len(ids) > rm_width:
                    raise DeferredOverflow(
                        f"replica {i} slot {s}: {len(ids)} parked keys > "
                        f"rm_width {rm_width}"
                    )
                kidx[i, s, : len(ids)] = ids
                dvalid[i, s] = True

        out.state = ops.SparseMVMapState(
            top=jnp.asarray(top), kid=jnp.asarray(kid), act=jnp.asarray(act),
            ctr=jnp.asarray(ctr), val=jnp.asarray(val), clk=jnp.asarray(clk),
            valid=jnp.asarray(valid), dcl=jnp.asarray(dcl),
            kidx=jnp.asarray(kidx), dvalid=jnp.asarray(dvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Map:
        st = jax.device_get(self._row(self.state, i))
        out = Map(MVReg)
        out.clock = VClock(
            {self.actors[a]: int(c) for a, c in enumerate(st.top) if c > 0}
        )
        for s in np.nonzero(st.valid)[0]:
            k = self.keys[int(st.kid[s])]
            d = Dot(self.actors[int(st.act[s])], int(st.ctr[s]))
            clock = VClock(
                {self.actors[a]: int(c)
                 for a, c in enumerate(st.clk[s]) if c > 0}
            )
            out.entries.setdefault(k, MVReg())
            out.entries[k].vals[d] = (clock, self.values[int(st.val[s])])
        for s in np.nonzero(st.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c)
                 for a, c in enumerate(st.dcl[s]) if c > 0}
            )
            out.deferred[clock] = {
                self.keys[int(k)] for k in st.kidx[s] if k >= 0
            }
        return out

    # ---- op path (CmRDT) ----------------------------------------------
    @transactional_apply("keys", "actors", "values")
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/map.rs ``CmRDT::apply``)."""
        if isinstance(op, Nop):
            return
        row = self._row(self.state, replica)
        na = self.state.top.shape[-1]
        if isinstance(op, Up):
            if not isinstance(op.op, Put):
                raise TypeError(
                    f"BatchedSparseMap routes MVReg ops only, got {op.op!r}"
                )
            strict_validate_dot(
                row.top, self.actors, op.dot.actor, op.dot.counter
            )
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            kid = self.keys.bounded_intern(op.key, self.n_keys, "key")
            cl = clock_lanes(
                op.op.clock, self.actors, na, dtype=self.state.top.dtype
            )
            row, overflow = ops.apply_up(
                row,
                jnp.asarray(aid),
                jnp.asarray(np.uint32(op.dot.counter)),
                jnp.asarray(kid),
                jnp.asarray(cl),
                jnp.asarray(self.values.intern(op.op.val)),
            )
            if bool(overflow):
                raise DotCapacityOverflow(
                    f"replica {replica}: cell table full on Up at key "
                    f"{op.key!r} — rebuild with a larger cell_cap"
                )
        elif isinstance(op, MapRm):
            cl = clock_lanes(
                op.clock, self.actors, na, dtype=self.state.top.dtype
            )
            q = self.state.kidx.shape[-1]
            ids = sorted(
                self.keys.bounded_intern(k, self.n_keys, "key")
                for k in op.keyset
            )
            if len(ids) > q:
                raise DeferredOverflow(
                    f"replica {replica}: rm keyset of {len(ids)} keys > "
                    f"rm_width {q}"
                )
            kids = np.full((q,), -1, np.int32)
            kids[: len(ids)] = ids
            row, overflow = ops.apply_rm(row, jnp.asarray(cl), jnp.asarray(kids))
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: deferred buffer full "
                    f"(cap {self.state.dvalid.shape[-1]})"
                )
        else:
            raise TypeError(f"not a Map op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r_: full.at[replica].set(r_), self.state, row
        )

    @transactional_apply("actors")
    def reset_remove(self, replica: int, clock) -> None:
        """``Causal::reset_remove`` on one replica (reference:
        src/map.rs ResetRemove impl; dense sibling:
        BatchedMap.reset_remove)."""
        cl = clock_lanes(
            clock, self.actors, self.state.top.shape[-1],
            dtype=self.state.top.dtype,
        )
        row = ops.reset_remove(self._row(self.state, replica), jnp.asarray(cl))
        self.state = jax.tree.map(
            lambda full, r_: full.at[replica].set(r_), self.state, row
        )

    # ---- state path (CvRDT) -------------------------------------------
    def _check(self, flags, what: str) -> None:
        cells, deferred, siblings = (bool(x) for x in flags)
        if cells:
            raise DotCapacityOverflow(
                f"{what}: cell table full — rebuild with a larger cell_cap"
            )
        if deferred:
            raise DeferredOverflow(
                f"{what}: deferred buffer full — rebuild with a larger "
                f"deferred_cap"
            )
        if siblings:
            raise SlotOverflow(
                f"{what}: a key exceeds sibling_cap concurrent writers"
            )

    def merge_from(self, dst: int, src: int) -> None:
        metrics.count("sparse_map.merges")
        joined, flags = ops.join(
            self._row(self.state, dst),
            self._row(self.state, src),
            sibling_cap=self.sibling_cap,
        )
        self._check(flags, f"merge {src}->{dst}")
        self.state = jax.tree.map(
            lambda full, r_: full.at[dst].set(r_), self.state, joined
        )

    def fold(self) -> Map:
        """Full-mesh anti-entropy: join all replicas, return the
        converged oracle-form state."""
        metrics.count("sparse_map.merges", max(self.n_replicas - 1, 0))
        observe_depth("sparse_map", self.state)
        folded, flags = ops.fold(self.state, sibling_cap=self.sibling_cap)
        self._check(flags, "fold")
        tmp = BatchedSparseMap(
            1, self.n_keys, self.state.top.shape[-1], self.cell_cap,
            self.sibling_cap, self.state.dvalid.shape[-1],
            self.state.kidx.shape[-1],
            keys=self.keys, actors=self.actors, values=self.values,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)

    def keys_of(self, i: int) -> frozenset:
        st = jax.device_get(self._row(self.state, i))
        return frozenset(
            self.keys[int(k)] for k in st.kid[st.valid] if k >= 0
        )

    def nbytes(self) -> int:
        return ops.nbytes(self.state)

    # ---- elastic capacity migration (elastic.py) ----------------------
    def widen_capacity(
        self,
        cell_cap: int = 0,
        n_keys: int = 0,
        n_actors: int = 0,
        sibling_cap: int = 0,
        deferred_cap: int = 0,
        rm_width: int = 0,
    ) -> None:
        """Cell-table repack into a wider layout in place — the
        sanctioned recovery from ``DotCapacityOverflow`` /
        ``SlotOverflow`` / ``DeferredOverflow`` / a full key universe
        (elastic.py drives this; the device migration is
        ``ops.sparse_mvmap.widen``). ``n_keys`` and ``sibling_cap`` are
        host-side bounds (the key universe is virtual and the sibling
        cap is a join-time check), so they update without touching
        device state — but the packed int32 cell key still bounds
        ``n_keys · n_actors``. 0 keeps a width; shrinking is refused."""
        na = n_actors or self.state.top.shape[-1]
        # An unpinned key bound auto-clamps to what the packing allows
        # at the (possibly wider) actor count; a pinned one must fit.
        nk = n_keys or min(self.n_keys, (2**31 - 1) // max(na, 1))
        if n_keys and n_keys < self.n_keys:
            raise ValueError("widen_capacity cannot shrink n_keys")
        if sibling_cap and sibling_cap < self.sibling_cap:
            raise ValueError("widen_capacity cannot shrink sibling_cap")
        if nk < len(self.keys):
            raise ValueError(
                f"n_keys = {nk} would orphan {len(self.keys)} "
                f"already-interned keys"
            )
        if nk * na > 2**31 - 1:
            raise ValueError(
                f"key universe too wide for the int32 packed-cell key: "
                f"n_keys * n_actors = {nk * na:,} > 2^31-1"
            )
        self.state = ops.widen(
            self.state, cell_cap, n_actors, deferred_cap, rm_width
        )
        self.n_keys = nk
        if sibling_cap:
            self.sibling_cap = sibling_cap

    def narrow_capacity(
        self,
        cell_cap: int = 0,
        n_keys: int = 0,
        n_actors: int = 0,
        sibling_cap: int = 0,
        deferred_cap: int = 0,
        rm_width: int = 0,
    ) -> None:
        """The inverse migration — slice the cell table down in place
        (elastic.shrink drives this under the hysteresis policy).
        ``ops.sparse_mvmap.narrow`` refuses when occupancy does not fit;
        the host-side bounds (``n_keys`` / ``sibling_cap``) only narrow
        down to what the interner / live sibling counts allow. 0 keeps
        a width."""
        if n_keys:
            if n_keys < len(self.keys):
                raise ValueError(
                    f"narrow refused: {len(self.keys)} keys interned > "
                    f"target n_keys {n_keys}"
                )
            self.n_keys = n_keys
        if n_actors and n_actors < len(self.actors):
            raise ValueError(
                f"narrow refused: {len(self.actors)} actors interned > "
                f"target n_actors {n_actors}"
            )
        if sibling_cap:
            from ..elastic import _max_siblings

            live = _max_siblings(self.state)
            if sibling_cap < live:
                raise ValueError(
                    f"narrow refused: {live} live siblings > target "
                    f"sibling_cap {sibling_cap}"
                )
            self.sibling_cap = sibling_cap
        self.state = ops.narrow(
            self.state, cell_cap, n_actors, deferred_cap, rm_width
        )
