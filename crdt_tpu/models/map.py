"""BatchedMap — N dense Map<K, MVReg<V>> replicas on device.

Oracle: ``crdt_tpu.pure.map.Map`` with ``MVReg`` children (reference:
src/map.rs specialised to the BASELINE config-4 shape ``Map<String,
MVReg<_>>``). The replica batch is an ``ops.map.MapState`` with leading
axis R over fixed interned key / actor / value universes. Conversion
to/from the oracle is lossless — content witness dots, sibling write
clocks, and the deferred-removal buffer included — which the
bit-identical A/B gate in tests/test_models_map.py exercises.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dot import Dot
from ..ops import map as ops
from ..ops import mvreg as mv_ops
from ..pure.map import Map, MapRm, Nop, Up
from ..pure.mvreg import MVReg, Put
from ..utils import Interner, clock_lanes, transactional_apply
from ..utils.metrics import metrics
from ..vclock import VClock
from .orswot import DeferredOverflow
from .registers import SlotOverflow
from .validation import strict_validate_dot


class BatchedMap:
    def __init__(
        self,
        n_replicas: int,
        n_keys: int,
        n_actors: int,
        sibling_cap: int = 4,
        deferred_cap: int = 4,
        keys: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
    ):
        self.keys = keys if keys is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.values = values if values is not None else Interner()
        self.state = ops.empty(
            n_keys, n_actors, sibling_cap, deferred_cap, batch=(n_replicas,)
        )

    @property
    def n_replicas(self) -> int:
        return self.state.top.shape[0]

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Map],
        keys: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        values: Optional[Interner] = None,
        sibling_cap: int = 4,
        deferred_cap: int = 4,
        n_keys: int = 0,
        n_actors: int = 0,
    ) -> "BatchedMap":
        """``n_keys`` / ``n_actors`` set capacity FLOORS above the names
        present in ``pures`` — spare lanes later ops intern into."""
        keys = keys if keys is not None else Interner()
        actors = actors if actors is not None else Interner()
        values = values if values is not None else Interner()
        for p in pures:
            for actor in p.clock.dots:
                actors.intern(actor)
            for k, child in p.entries.items():
                keys.intern(k)
                if not isinstance(child, MVReg):
                    raise TypeError(
                        f"BatchedMap children must be MVReg, got {type(child)}"
                    )
                for d, (clock, v) in child.vals.items():
                    actors.intern(d.actor)
                    for actor in clock.dots:
                        actors.intern(actor)
                    values.intern(v)
            for clock, ks in p.deferred.items():
                for actor in clock.dots:
                    actors.intern(actor)
                for k in ks:
                    keys.intern(k)

        r = len(pures)
        nk, na = max(len(keys), n_keys, 1), max(len(actors), n_actors, 1)
        out = cls(
            r, nk, na, sibling_cap, deferred_cap,
            keys=keys, actors=actors, values=values,
        )
        top = np.zeros((r, na), np.uint32)
        cact = np.zeros((r, nk, sibling_cap), np.int32)
        cctr = np.zeros((r, nk, sibling_cap), np.uint32)
        cclk = np.zeros((r, nk, sibling_cap, na), np.uint32)
        cval = np.zeros((r, nk, sibling_cap), np.int32)
        cvalid = np.zeros((r, nk, sibling_cap), bool)
        dcl = np.zeros((r, deferred_cap, na), np.uint32)
        dkeys = np.zeros((r, deferred_cap, nk), bool)
        dvalid = np.zeros((r, deferred_cap), bool)
        for i, p in enumerate(pures):
            for actor, c in p.clock.dots.items():
                top[i, actors.id_of(actor)] = c
            for k, child in p.entries.items():
                ki = keys.id_of(k)
                if len(child.vals) > sibling_cap:
                    raise ValueError(
                        f"replica {i} key {k!r}: {len(child.vals)} "
                        f"siblings; capacity is {sibling_cap}"
                    )
                # Canonical slot order (actor id, counter) — matches the
                # kernels' _canon_child, so raw arrays are comparable.
                for s, (d, (clock, v)) in enumerate(
                    sorted(
                        child.vals.items(),
                        key=lambda kv: (actors.id_of(kv[0].actor), kv[0].counter),
                    )
                ):
                    cact[i, ki, s] = actors.id_of(d.actor)
                    cctr[i, ki, s] = d.counter
                    for actor, c in clock.dots.items():
                        cclk[i, ki, s, actors.id_of(actor)] = c
                    cval[i, ki, s] = values.id_of(v)
                    cvalid[i, ki, s] = True
            if len(p.deferred) > deferred_cap:
                raise ValueError(
                    f"replica {i} has {len(p.deferred)} deferred removes; "
                    f"capacity is {deferred_cap}"
                )
            for d, (clock, ks) in enumerate(p.deferred.items()):
                for actor, c in clock.dots.items():
                    dcl[i, d, actors.id_of(actor)] = c
                for k in ks:
                    dkeys[i, d, keys.id_of(k)] = True
                dvalid[i, d] = True

        out.state = ops.MapState(
            top=jnp.asarray(top),
            child=mv_ops.MVRegState(
                wact=jnp.asarray(cact),
                wctr=jnp.asarray(cctr),
                clk=jnp.asarray(cclk),
                val=jnp.asarray(cval),
                valid=jnp.asarray(cvalid),
            ),
            dcl=jnp.asarray(dcl),
            dkeys=jnp.asarray(dkeys),
            dvalid=jnp.asarray(dvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Map:
        st = jax.device_get(self._row(self.state, i))
        out = Map(MVReg)
        out.clock = VClock(
            {self.actors[a]: int(c) for a, c in enumerate(st.top) if c > 0}
        )
        present = st.child.valid.any(axis=-1)
        for ki in np.nonzero(present)[0]:
            vals = {}
            for s in np.nonzero(st.child.valid[ki])[0]:
                d = Dot(
                    self.actors[int(st.child.wact[ki, s])],
                    int(st.child.wctr[ki, s]),
                )
                clock = VClock(
                    {
                        self.actors[a]: int(c)
                        for a, c in enumerate(st.child.clk[ki, s])
                        if c > 0
                    }
                )
                vals[d] = (clock, self.values[int(st.child.val[ki, s])])
            out.entries[self.keys[int(ki)]] = MVReg(vals)
        for d in np.nonzero(st.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.dcl[d]) if c > 0}
            )
            out.deferred[clock] = {
                self.keys[int(k)] for k in np.nonzero(st.dkeys[d])[0]
            }
        return out

    # ---- op path (CmRDT) ----------------------------------------------
    @transactional_apply("keys", "actors", "values")
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/map.rs ``CmRDT::apply``)."""
        if isinstance(op, Nop):
            return
        row = self._row(self.state, replica)
        if isinstance(op, Up):
            if not isinstance(op.op, Put):
                raise TypeError(
                    f"BatchedMap routes MVReg ops only, got {op.op!r}"
                )
            na = self.state.top.shape[-1]
            nk = self.state.dkeys.shape[-1]
            strict_validate_dot(row.top, self.actors, op.dot.actor, op.dot.counter)
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            kid = self.keys.bounded_intern(op.key, nk, "key")
            clock = clock_lanes(
                op.op.clock, self.actors, na, dtype=self.state.top.dtype
            )
            row, overflow = ops.apply_up(
                row,
                jnp.asarray(aid),
                jnp.asarray(np.uint32(op.dot.counter)),
                jnp.asarray(kid),
                jnp.asarray(clock),
                jnp.asarray(self.values.intern(op.op.val)),
            )
            if bool(overflow):
                raise SlotOverflow(
                    f"replica {replica}: sibling slab full on Up at key "
                    f"{op.key!r} — rebuild with a larger sibling_cap"
                )
        elif isinstance(op, MapRm):
            na = self.state.top.shape[-1]
            cl = clock_lanes(
                op.clock, self.actors, na, dtype=self.state.top.dtype
            )
            mask = np.zeros((self.state.dkeys.shape[-1],), bool)
            for k in op.keyset:
                mask[self.keys.bounded_intern(k, self.state.dkeys.shape[-1], "key")] = True
            row, overflow = ops.apply_rm(row, jnp.asarray(cl), jnp.asarray(mask))
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: deferred buffer full "
                    f"(cap {self.state.dvalid.shape[-1]})"
                )
        else:
            raise TypeError(f"not a Map op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    @transactional_apply("actors")
    def reset_remove(self, replica: int, clock) -> None:
        """``Causal::reset_remove`` on one replica: nested causal
        removal — children drop contents whose witness dot the given
        ``VClock`` covers, bottomed keys die, parked removes and the
        outer clock forget covered lanes (reference: src/map.rs
        ResetRemove impl; oracle: pure/map.py ``reset_remove``)."""
        cl = clock_lanes(
            clock, self.actors, self.state.top.shape[-1],
            dtype=self.state.top.dtype,
        )
        row = ops.reset_remove(self._row(self.state, replica), jnp.asarray(cl))
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    # ---- state path (CvRDT — the config-4 benchmark path) -------------
    @staticmethod
    def _check_join_flags(flags, what: str) -> None:
        """The join's flag lanes: [sibling-slab, deferred-buffer]."""
        sibling, deferred = (bool(x) for x in flags)
        if sibling:
            raise SlotOverflow(
                f"{what}: sibling slab full — rebuild with a larger sibling_cap"
            )
        if deferred:
            raise DeferredOverflow(
                f"{what}: deferred buffer full — rebuild with a larger deferred_cap"
            )

    def merge_from(self, dst: int, src: int) -> None:
        metrics.count("map.merges")
        joined, flags = ops.join(
            self._row(self.state, dst), self._row(self.state, src)
        )
        self._check_join_flags(flags, f"merge {src}->{dst}")
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, joined
        )

    def fold(self) -> Map:
        """Full-mesh anti-entropy: join all R replicas in a log2 reduction
        tree and return the converged oracle-form state."""
        metrics.count("map.merges", max(self.n_replicas - 1, 0))
        metrics.observe(
            "map.deferred_depth",
            float(jnp.sum(self.state.dvalid)) / max(self.n_replicas, 1),
        )
        folded, flags = ops.fold(self.state)
        self._check_join_flags(flags, "fold")
        tmp = BatchedMap(
            1,
            self.state.dkeys.shape[-1],
            self.state.top.shape[-1],
            self.state.child.wact.shape[-1],
            self.state.dcl.shape[-2],
            keys=self.keys,
            actors=self.actors,
            values=self.values,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)

    def keys_of(self, i: int) -> frozenset:
        present = np.asarray(self.state.child.valid[i].any(axis=-1))
        return frozenset(self.keys[int(k)] for k in np.nonzero(present)[0])

    # ---- elastic capacity migration (elastic.py) ----------------------
    def widen_capacity(
        self,
        n_keys: int = 0,
        n_actors: int = 0,
        sibling_cap: int = 0,
        deferred_cap: int = 0,
    ) -> None:
        """Re-encode the live device state into a wider layout in place
        — the sanctioned recovery from ``SlotOverflow`` /
        ``DeferredOverflow`` / a full key universe (elastic.py drives
        this; the migration is ``ops.map.widen`` riding
        ``ops.mvreg.widen`` for the sibling slab). 0 keeps a width;
        interners and ids are untouched and the result is bit-identical
        to a from-scratch model built at the wider capacity."""
        self.state = ops.widen(
            self.state, n_keys, n_actors, sibling_cap, deferred_cap
        )

    def narrow_capacity(
        self,
        n_keys: int = 0,
        n_actors: int = 0,
        sibling_cap: int = 0,
        deferred_cap: int = 0,
    ) -> None:
        """The inverse migration — re-encode into a NARROWER layout in
        place (elastic.shrink drives this under the hysteresis policy).
        Refuses when a dropped lane holds live state or an interned
        name's lane (``ops.map.narrow`` checks the device planes). 0
        keeps a width."""
        if n_keys and n_keys < len(self.keys):
            raise ValueError(
                f"narrow refused: {len(self.keys)} keys interned > "
                f"target n_keys {n_keys}"
            )
        if n_actors and n_actors < len(self.actors):
            raise ValueError(
                f"narrow refused: {len(self.actors)} actors interned > "
                f"target n_actors {n_actors}"
            )
        self.state = ops.narrow(
            self.state, n_keys, n_actors, sibling_cap, deferred_cap
        )
