"""BatchedMap3 — N dense ``Map<K1, Map<K2, Orswot<M>>>`` replicas.

Oracle: ``crdt_tpu.pure.map.Map`` with nested ``Map(Orswot)`` children
(reference: src/map.rs ``V: Val<A>`` at depth 3). Device form per
ops/map3.py: the depth-2 ``map_orswot`` slab over the K1×K2 product key
space plus one more outer deferred buffer — the slab-composition
induction step applied once more (SURVEY.md §7.1).

Conversions are lossless across all THREE deferred levels (leaf member
removes, K2 keyset removes, K1 keyset removes), which the A/B gates in
tests/test_models_map3.py exercise.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import map3 as ops
from ..pure.map import Map, MapRm, Nop, Up
from ..pure.orswot import Add as OrswotAdd, Orswot, Rm as OrswotRm
from ..utils import Interner, clock_lanes, transactional_apply
from ..utils.metrics import metrics, observe_depth
from ..vclock import VClock
from .orswot import DeferredOverflow
from .validation import strict_validate_dot


class BatchedMap3:
    def __init__(
        self,
        n_replicas: int,
        n_keys1: int,
        n_keys2: int,
        n_members: int,
        n_actors: int,
        deferred_cap: int = 4,
        keys1: Optional[Interner] = None,
        keys2: Optional[Interner] = None,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
    ):
        self.keys1 = keys1 if keys1 is not None else Interner()
        self.keys2 = keys2 if keys2 is not None else Interner()
        self.members = members if members is not None else Interner()
        self.actors = actors if actors is not None else Interner()
        self.state = ops.empty(
            n_keys1, n_keys2, n_members, n_actors, deferred_cap,
            batch=(n_replicas,),
        )

    @property
    def n_replicas(self) -> int:
        return self.state.mo.core.top.shape[0]

    @property
    def n_keys1(self) -> int:
        return self.state.odkeys.shape[-1]

    @property
    def n_keys2(self) -> int:
        return self.state.mo.kdkeys.shape[-1] // self.n_keys1

    @property
    def n_members(self) -> int:
        return self.state.mo.core.ctr.shape[-2] // self.state.mo.kdkeys.shape[-1]

    # ---- conversion (the A/B gate boundary) ---------------------------
    @classmethod
    def from_pure(
        cls,
        pures: Sequence[Map],
        deferred_cap: int = 4,
        keys1: Optional[Interner] = None,
        keys2: Optional[Interner] = None,
        members: Optional[Interner] = None,
        actors: Optional[Interner] = None,
        n_keys1: int = 1,
        n_keys2: int = 1,
        n_members: int = 1,
        n_actors: int = 1,
    ) -> "BatchedMap3":
        keys1 = keys1 if keys1 is not None else Interner()
        keys2 = keys2 if keys2 is not None else Interner()
        members = members if members is not None else Interner()
        actors = actors if actors is not None else Interner()
        for p in pures:
            for actor in p.clock.dots:
                actors.intern(actor)
            for k1, child in p.entries.items():
                keys1.intern(k1)
                if not isinstance(child, Map):
                    raise TypeError(
                        f"BatchedMap3 children must be Map, got {type(child)}"
                    )
                if child.clock != p.clock:
                    raise ValueError(
                        f"child at {k1!r} violates the covered invariant "
                        f"(child clock != map clock); not a composed state"
                    )
                for k2, leaf in child.entries.items():
                    keys2.intern(k2)
                    if not isinstance(leaf, Orswot):
                        raise TypeError(
                            f"leaf children must be Orswot, got {type(leaf)}"
                        )
                    if leaf.clock != p.clock:
                        raise ValueError(
                            f"leaf at ({k1!r},{k2!r}) violates the covered "
                            f"invariant; not a composed state"
                        )
                    for m, clock in leaf.entries.items():
                        members.intern(m)
                        for actor in clock.dots:
                            actors.intern(actor)
                    for clock, ms in leaf.deferred.items():
                        for actor in clock.dots:
                            actors.intern(actor)
                        for m in ms:
                            members.intern(m)
                for clock, k2s in child.deferred.items():
                    for actor in clock.dots:
                        actors.intern(actor)
                    for k2 in k2s:
                        keys2.intern(k2)
            for clock, k1s in p.deferred.items():
                for actor in clock.dots:
                    actors.intern(actor)
                for k1 in k1s:
                    keys1.intern(k1)

        r = len(pures)
        nk1 = max(len(keys1), n_keys1, 1)
        nk2 = max(len(keys2), n_keys2, 1)
        nm = max(len(members), n_members, 1)
        na = max(len(actors), n_actors, 1)
        out = cls(
            r, nk1, nk2, nm, na, deferred_cap,
            keys1=keys1, keys2=keys2, members=members, actors=actors,
        )
        d = deferred_cap
        nk = nk1 * nk2
        top = np.zeros((r, na), np.uint32)
        ctr = np.zeros((r, nk * nm, na), np.uint32)
        dcl = np.zeros((r, d, na), np.uint32)       # leaf member removes
        dmask = np.zeros((r, d, nk * nm), bool)
        dvalid = np.zeros((r, d), bool)
        kdcl = np.zeros((r, d, na), np.uint32)      # K2 keyset removes
        kdkeys = np.zeros((r, d, nk), bool)
        kdvalid = np.zeros((r, d), bool)
        odcl = np.zeros((r, d, na), np.uint32)      # K1 keyset removes
        odkeys = np.zeros((r, d, nk1), bool)
        odvalid = np.zeros((r, d), bool)
        for i, p in enumerate(pures):
            for actor, c in p.clock.dots.items():
                top[i, actors.id_of(actor)] = c
            leafd: dict = {}
            midd: dict = {}
            for k1, child in p.entries.items():
                k1i = keys1.id_of(k1)
                for k2, leaf in child.entries.items():
                    ki = k1i * nk2 + keys2.id_of(k2)
                    for m, clock in leaf.entries.items():
                        mi = members.id_of(m)
                        for actor, c in clock.dots.items():
                            ctr[i, ki * nm + mi, actors.id_of(actor)] = c
                    for clock, ms in leaf.deferred.items():
                        leafd.setdefault(clock, set()).update(
                            ki * nm + members.id_of(m) for m in ms
                        )
                for clock, k2s in child.deferred.items():
                    midd.setdefault(clock, set()).update(
                        k1i * nk2 + keys2.id_of(k2) for k2 in k2s
                    )
            for what, parked, cap in (
                ("leaf", leafd, d), ("middle", midd, d),
            ):
                if len(parked) > cap:
                    raise ValueError(
                        f"replica {i}: {len(parked)} {what} parked removes; "
                        f"capacity is {cap}"
                    )
            for s, (clock, cells) in enumerate(leafd.items()):
                for actor, c in clock.dots.items():
                    dcl[i, s, actors.id_of(actor)] = c
                for cell in cells:
                    dmask[i, s, cell] = True
                dvalid[i, s] = True
            for s, (clock, cells) in enumerate(midd.items()):
                for actor, c in clock.dots.items():
                    kdcl[i, s, actors.id_of(actor)] = c
                for cell in cells:
                    kdkeys[i, s, cell] = True
                kdvalid[i, s] = True
            if len(p.deferred) > d:
                raise ValueError(
                    f"replica {i}: {len(p.deferred)} outer parked removes; "
                    f"capacity is {d}"
                )
            for s, (clock, k1s) in enumerate(p.deferred.items()):
                for actor, c in clock.dots.items():
                    odcl[i, s, actors.id_of(actor)] = c
                for k1 in k1s:
                    odkeys[i, s, keys1.id_of(k1)] = True
                odvalid[i, s] = True

        core = out.state.mo.core._replace(
            top=jnp.asarray(top),
            ctr=jnp.asarray(ctr),
            dcl=jnp.asarray(dcl),
            dmask=jnp.asarray(dmask),
            dvalid=jnp.asarray(dvalid),
        )
        out.state = ops.Map3State(
            mo=ops.MapOrswotState(
                core=core,
                kdcl=jnp.asarray(kdcl),
                kdkeys=jnp.asarray(kdkeys),
                kdvalid=jnp.asarray(kdvalid),
            ),
            odcl=jnp.asarray(odcl),
            odkeys=jnp.asarray(odkeys),
            odvalid=jnp.asarray(odvalid),
        )
        return out

    def _row(self, arrs, i: int):
        return jax.tree.map(lambda x: x[i], arrs)

    def to_pure(self, i: int) -> Map:
        st = jax.device_get(self._row(self.state, i))
        nk1, nk2, nm = self.n_keys1, self.n_keys2, self.n_members
        out = Map(val_default=lambda: Map(val_default=Orswot))
        out.clock = VClock(
            {self.actors[a]: int(c) for a, c in enumerate(st.mo.core.top) if c > 0}
        )
        ctr = st.mo.core.ctr.reshape(nk1, nk2, nm, -1)
        for k1i in np.nonzero(ctr.any(axis=(1, 2, 3)))[0]:
            child = Map(val_default=Orswot)
            child.clock = out.clock.clone()
            for k2i in np.nonzero(ctr[k1i].any(axis=(1, 2)))[0]:
                leaf = Orswot()
                leaf.clock = out.clock.clone()
                for mi in np.nonzero(ctr[k1i, k2i].any(axis=-1))[0]:
                    leaf.entries[self.members[int(mi)]] = VClock(
                        {
                            self.actors[a]: int(c)
                            for a, c in enumerate(ctr[k1i, k2i, mi])
                            if c > 0
                        }
                    )
                child.entries[self.keys2[int(k2i)]] = leaf
            out.entries[self.keys1[int(k1i)]] = child
        # Leaf parked member-removes: split each shared slot per (k1, k2).
        for s in np.nonzero(st.mo.core.dvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.mo.core.dcl[s]) if c > 0}
            )
            mask = st.mo.core.dmask[s].reshape(nk1, nk2, nm)
            for k1i, k2i in zip(*np.nonzero(mask.any(axis=-1))):
                child = out.entries.get(self.keys1[int(k1i)])
                leaf = (
                    child.entries.get(self.keys2[int(k2i)])
                    if child is not None
                    else None
                )
                if leaf is None:
                    continue  # scrubbed dead key (oracle dropped it too)
                leaf.deferred.setdefault(clock.clone(), set()).update(
                    self.members[int(mi)]
                    for mi in np.nonzero(mask[k1i, k2i])[0]
                )
        # Middle (K2) parked keyset-removes: split per k1.
        for s in np.nonzero(st.mo.kdvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.mo.kdcl[s]) if c > 0}
            )
            mask = st.mo.kdkeys[s].reshape(nk1, nk2)
            for k1i in np.nonzero(mask.any(axis=-1))[0]:
                child = out.entries.get(self.keys1[int(k1i)])
                if child is None:
                    continue
                child.deferred.setdefault(clock.clone(), set()).update(
                    self.keys2[int(k2i)] for k2i in np.nonzero(mask[k1i])[0]
                )
        for s in np.nonzero(st.odvalid)[0]:
            clock = VClock(
                {self.actors[a]: int(c) for a, c in enumerate(st.odcl[s]) if c > 0}
            )
            out.deferred[clock] = {
                self.keys1[int(k)] for k in np.nonzero(st.odkeys[s])[0]
            }
        return out

    # ---- op path (CmRDT) ----------------------------------------------
    @transactional_apply("keys1", "keys2", "members", "actors")
    def apply(self, replica: int, op) -> None:
        """Apply an oracle-shaped op to one replica (reference:
        src/map.rs ``CmRDT::apply`` routing through two map levels)."""
        if isinstance(op, Nop):
            return
        row = self._row(self.state, replica)
        na = self.state.mo.core.top.shape[-1]
        nk1, nk2, nm = self.n_keys1, self.n_keys2, self.n_members
        if isinstance(op, Up):
            strict_validate_dot(
                row.mo.core.top, self.actors, op.dot.actor, op.dot.counter
            )
            k1id = self.keys1.bounded_intern(op.key, nk1, "outer key")
            aid = self.actors.bounded_intern(op.dot.actor, na, "actor")
            mid = op.op
            if isinstance(mid, Up):
                if mid.dot != op.dot:
                    raise ValueError(
                        "inner Up dot must equal the outer Up dot (one AddCtx)"
                    )
                k2id = self.keys2.bounded_intern(mid.key, nk2, "inner key")
                leaf_op = mid.op
                if isinstance(leaf_op, OrswotAdd):
                    if leaf_op.dot != op.dot:
                        raise ValueError(
                            "leaf add dot must equal the Up dot (one AddCtx)"
                        )
                    mask = np.zeros((nm,), bool)
                    for m in leaf_op.members:
                        mask[self.members.bounded_intern(m, nm, "member")] = True
                    row = ops.apply_member_add(
                        row,
                        jnp.asarray(aid),
                        jnp.asarray(np.uint32(op.dot.counter)),
                        jnp.asarray(k1id),
                        jnp.asarray(k2id),
                        jnp.asarray(mask),
                    )
                elif isinstance(leaf_op, OrswotRm):
                    clock = clock_lanes(leaf_op.clock, self.actors, na)
                    mask = np.zeros((nm,), bool)
                    for m in leaf_op.members:
                        mask[self.members.bounded_intern(m, nm, "member")] = True
                    row, overflow = ops.apply_member_rm(
                        row,
                        jnp.asarray(aid),
                        jnp.asarray(np.uint32(op.dot.counter)),
                        jnp.asarray(k1id),
                        jnp.asarray(k2id),
                        jnp.asarray(clock),
                        jnp.asarray(mask),
                    )
                    if bool(overflow):
                        raise DeferredOverflow(
                            f"replica {replica}: leaf deferred buffer full "
                            f"(cap {self.state.mo.core.dvalid.shape[-1]})"
                        )
                else:
                    raise TypeError(
                        f"leaf ops must be Orswot ops, got {leaf_op!r}"
                    )
            elif isinstance(mid, MapRm):
                clock = clock_lanes(mid.clock, self.actors, na)
                mask = np.zeros((nk2,), bool)
                for k2 in mid.keyset:
                    mask[self.keys2.bounded_intern(k2, nk2, "inner key")] = True
                row, overflow = ops.apply_key2_rm(
                    row,
                    jnp.asarray(aid),
                    jnp.asarray(np.uint32(op.dot.counter)),
                    jnp.asarray(k1id),
                    jnp.asarray(clock),
                    jnp.asarray(mask),
                )
                if bool(overflow):
                    raise DeferredOverflow(
                        f"replica {replica}: K2 deferred buffer full "
                        f"(cap {self.state.mo.kdvalid.shape[-1]})"
                    )
            else:
                raise TypeError(
                    f"BatchedMap3 routes Map ops only, got {mid!r}"
                )
        elif isinstance(op, MapRm):
            clock = clock_lanes(op.clock, self.actors, na)
            mask = np.zeros((nk1,), bool)
            for k1 in op.keyset:
                mask[self.keys1.bounded_intern(k1, nk1, "outer key")] = True
            row, overflow = ops.apply_key1_rm(
                row, jnp.asarray(clock), jnp.asarray(mask)
            )
            if bool(overflow):
                raise DeferredOverflow(
                    f"replica {replica}: outer deferred buffer full "
                    f"(cap {self.state.odvalid.shape[-1]})"
                )
        else:
            raise TypeError(f"not a Map op: {op!r}")
        self.state = jax.tree.map(
            lambda full, r: full.at[replica].set(r), self.state, row
        )

    # ---- state path (CvRDT) -------------------------------------------
    def _check_flags(self, flags, what: str) -> None:
        leaf, mid, outer = (bool(x) for x in flags)
        if leaf or mid or outer:
            level = "leaf" if leaf else ("K2" if mid else "K1")
            raise DeferredOverflow(
                f"{what}: {level} deferred buffer full — rebuild with a "
                f"larger deferred_cap"
            )

    def merge_from(self, dst: int, src: int) -> None:
        metrics.count("map3.merges")
        joined, flags = ops.join(
            self._row(self.state, dst), self._row(self.state, src)
        )
        self._check_flags(flags, f"merge {src}->{dst}")
        self.state = jax.tree.map(
            lambda full, r: full.at[dst].set(r), self.state, joined
        )

    def fold(self) -> Map:
        """Full-mesh anti-entropy: join all replicas, return the converged
        oracle-form state."""
        metrics.count("map3.merges", max(self.n_replicas - 1, 0))
        observe_depth("map3", self.state)
        folded, flags = ops.fold(self.state)
        self._check_flags(flags, "fold")
        tmp = BatchedMap3(
            1, self.n_keys1, self.n_keys2, self.n_members,
            self.state.mo.core.top.shape[-1],
            self.state.odcl.shape[-2],
            keys1=self.keys1, keys2=self.keys2,
            members=self.members, actors=self.actors,
        )
        tmp.state = jax.tree.map(lambda x: x[None], folded)
        return tmp.to_pure(0)
