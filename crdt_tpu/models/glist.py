"""BatchedGList — N device GList replicas over a shared identifier
universe.

Oracle: ``crdt_tpu.pure.glist.GList`` (reference: src/glist.rs). A GList
is a grow-only ordered SET of identifiers, so the device form is even
leaner than the List's: the shared universe (native engine, insert-only
trace) fixes every identifier's slot in total order and its element
payload, and a replica is just an ``alive bool[R, N]`` membership mask.
Merge is set union — a single elementwise OR — and full-mesh
anti-entropy over R replicas is ``alive.any(axis=0)``.

Identifier allocation note: the engine mints LSEQ-style (index, actor,
counter) tree paths while the pure ``between`` embeds the element as the
final marker — allocation strategies are an implementation choice in
the reference too, so the A/B gates (tests/test_streamed_lists.py for
sequence/merge/convergence behavior, tests/test_checkpoint.py for the
persisted identifier universe) drive both sides with ENGINE-minted
identifiers (via ``to_pure``-shaped ops) and check bit-identically.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dot import OrdDot
from ..native import INSERT, ListEngine
from ..pure.glist import GList, Insert
from ..pure.identifier import Identifier
from .list import growth_permutation


class BatchedGList:
    def __init__(self, n_replicas: int):
        self.engine = ListEngine()
        self.slots = np.empty(0, np.int64)  # rank per handle
        self.uvals = np.empty(0, np.int32)  # element payload per handle
        self.alive = jnp.zeros((n_replicas, 1), bool)

    @property
    def n_replicas(self) -> int:
        return self.alive.shape[0]

    # ---- universe growth (identifier minting) -------------------------
    def mint_inserts(
        self,
        indices: Sequence[int],
        values: Sequence[int],
        actors: Sequence[int],
    ) -> np.ndarray:
        """Mint identifiers for inserts at positions in the UNIVERSE
        sequence (every identifier ever minted — grow-only, nothing
        dies), growing the shared slot space. Returns the ops' handles;
        deliver them to replicas with :meth:`apply_inserts`."""
        kinds = np.full(len(indices), INSERT, np.uint8)
        handles = self.engine.apply_trace(kinds, indices, values, actors)
        self.uvals = np.concatenate(
            [self.uvals, np.ascontiguousarray(values, np.int32)]
        )
        new_rank = self.engine.total_order()
        src = growth_permutation(self.slots, new_rank)
        self.alive = _remap_alive(self.alive, jnp.asarray(src))
        self.slots = new_rank
        return handles

    # ---- op path (CmRDT: Insert delivery) -----------------------------
    def apply_inserts(self, replica_handles: np.ndarray) -> None:
        """One epoch: ``replica_handles[r]`` lists identifier handles
        replica ``r`` receives (shape [R, C]; -1 pads). One scatter for
        all replicas."""
        replica_handles = np.asarray(replica_handles)
        if replica_handles.ndim != 2 or replica_handles.shape[0] != self.n_replicas:
            raise ValueError(f"expected [R={self.n_replicas}, C] handles")
        valid = replica_handles >= 0
        safe = np.where(valid, replica_handles, 0)
        n = self.alive.shape[1]
        slots = jnp.asarray(np.where(valid, self.slots[safe], n))
        self.alive = self.alive.at[
            jnp.arange(self.n_replicas)[:, None], slots
        ].set(True, mode="drop")

    # ---- state path (CvRDT: union merge) ------------------------------
    def union_from(self, dst: int, src: int) -> None:
        """Set-union merge (reference: src/glist.rs ``CvRDT::merge``)."""
        self.alive = self.alive.at[dst].set(self.alive[dst] | self.alive[src])

    def fold(self) -> np.ndarray:
        """Full-mesh anti-entropy: the union of every replica's set."""
        return np.asarray(jnp.any(self.alive, axis=0))

    # ---- reads ---------------------------------------------------------
    def read(self, replica: Optional[int] = None) -> list:
        """The replica's element sequence (None = the folded union)."""
        mask = (
            self.fold() if replica is None else np.asarray(self.alive[replica])
        )
        if len(self.slots) == 0:
            return []
        vals_in_slot_order = np.empty(len(self.slots), np.int32)
        vals_in_slot_order[self.slots] = self.uvals
        return vals_in_slot_order[mask[: len(self.slots)]].tolist()

    def identifier(self, handle: int) -> Identifier:
        """The engine-minted identifier for a handle, in oracle form."""
        path = self.engine.identifier_path(int(handle))
        return Identifier(
            tuple((ix, OrdDot(a, c)) for ix, a, c in path)
        )

    def to_pure(self, replica: Optional[int] = None) -> GList:
        """Oracle form of one replica (None = the folded union) with the
        engine's identifiers."""
        mask = (
            self.fold() if replica is None else np.asarray(self.alive[replica])
        )
        out = GList()
        handle_of_slot = np.argsort(self.slots, kind="stable")
        for slot in range(len(self.slots)):
            if mask[slot]:
                out.apply(Insert(id=self.identifier(handle_of_slot[slot])))
        return out


@jax.jit
def _remap_alive(alive, src):
    safe = jnp.where(src >= 0, src, 0)
    return jnp.where(src[None, :] < 0, False, alive[:, safe])
