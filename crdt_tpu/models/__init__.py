"""Batched, device-resident CRDT replica containers.

Each model holds N replicas of one CRDT type as struct-of-arrays device
state (SURVEY.md §7.1) and exposes:

- the op path (``apply_*``) and the state path (``merge`` / ``fold``)
  running as ``crdt_tpu.ops`` kernels,
- lossless conversion to/from the ``crdt_tpu.pure`` oracle types
  (``to_pure`` / ``from_pure``), which is how the bit-identical A/B gate
  in tests/ is enforced.
"""

from .vclock import BatchedVClock
from .counters import BatchedGCounter, BatchedPNCounter
from .orswot import BatchedOrswot
from .sparse_map import BatchedSparseMapOrswot
from .sparse_mvmap import BatchedSparseMap
from .sparse_nested_map import BatchedSparseNestedMap
from .sparse_orswot import BatchedSparseOrswot
from .gset import BatchedGSet
from .registers import BatchedLWWReg, BatchedMVReg, SlotOverflow
from .map import BatchedMap
from .map3 import BatchedMap3
from .map_nested import BatchedMapOrswot, BatchedNestedMap
from .list import BatchedList
from .glist import BatchedGList

__all__ = [
    "BatchedVClock",
    "BatchedGCounter",
    "BatchedPNCounter",
    "BatchedOrswot",
    "BatchedSparseMap",
    "BatchedSparseMapOrswot",
    "BatchedSparseNestedMap",
    "BatchedSparseOrswot",
    "BatchedGSet",
    "BatchedLWWReg",
    "BatchedMVReg",
    "BatchedMap",
    "BatchedMap3",
    "BatchedMapOrswot",
    "BatchedNestedMap",
    "BatchedList",
    "BatchedGList",
    "SlotOverflow",
]
