"""Strict-mode op validation for the batched (xla) path.

Reference: src/traits.rs v7 ``CmRDT::validate_op`` + src/dot.rs
``DotRange`` (SURVEY.md §3.2 checklist). The pure oracle validates per
type; the batched models share one rule: under ``config.strict`` an
op's witness dot must be the actor's next contiguous event for the
receiving replica — a duplicate or gapped dot raises ``DotRange``
instead of being silently dropped/misapplied. Costs one device→host
scalar read per apply, which is the point of it being a strict/debug
mode."""

from __future__ import annotations

import numpy as np

from ..traits import CounterSaturation, DotRange


def _dtype_max(dtype) -> int:
    return int(np.iinfo(np.dtype(str(dtype))).max)


def strict_validate_dot(top_row, actors, actor, counter: int) -> None:
    """Raise DotRange unless ``counter`` is the next contiguous event of
    ``actor`` against this replica's top clock, and CounterSaturation if
    the lane has reached its dtype maximum (the u32 overflow trap —
    SURVEY.md §7.3 "overflow discipline"; the next mint would wrap and
    silently break clock monotonicity). No-op unless ``config.strict``.

    Takes the interner (not a lane id) so validation can run BEFORE any
    lane is allocated — a rejected op must be side-effect free, like the
    oracle's ``validate_op`` (a never-seen actor's expected counter
    is 1)."""
    from ..config import config

    if not config.strict:
        return
    arr = np.asarray(top_row)
    seen = 0
    if actor in actors:
        aid = actors.id_of(actor)
        if aid < arr.shape[-1]:
            seen = int(arr[aid])
    limit = _dtype_max(arr.dtype)
    if seen >= limit:
        raise CounterSaturation(actor, seen, limit)
    if int(counter) != seen + 1:
        raise DotRange(actor, int(counter), seen + 1)


def strict_check_headroom(lane_value, actor, steps: int, dtype) -> None:
    """Counter-increment headroom trap: raise CounterSaturation when a
    ``steps``-sized add would exceed the lane dtype's maximum. No-op
    unless ``config.strict`` (the unchecked path wraps, as documented in
    the u32 envelope note — config.counter_dtype)."""
    from ..config import config

    if not config.strict:
        return
    limit = _dtype_max(dtype)
    if int(lane_value) + int(steps) > limit:
        raise CounterSaturation(actor, int(lane_value), limit)
