"""Metrics exporter: drain the observability layer to Prometheus + JSONL.

Three producers feed one drain:

- the host registry (``utils.metrics.metrics`` — counters/gauges,
  including the ``elastic.<kind>.headroom.<axis>`` pressure gauges),
- concrete :class:`crdt_tpu.telemetry.Telemetry` pytrees returned by
  the mesh entry points (``telemetry=True``) — scalar counters AND the
  ``hist_*`` in-kernel histogram subtrees (crdt_tpu/obs/hist.py),
- span trace events buffered by ``telemetry.span``.

Three sinks:

- :func:`prometheus_text` — the Prometheus text exposition format
  (``# TYPE``-annotated; dotted metric names sanitized to underscores,
  gauge min/max/sum/count exploded into suffixed series, histogram
  fields rendered as conformant cumulative ``_bucket{le=...}`` /
  ``_sum`` / ``_count`` series) for scrape endpoints or textfile
  collectors;
- :func:`drain_jsonl` — append-only JSONL, one self-describing record
  per line (``{"record": "snapshot"|"telemetry"|"span", "ts": ...}``),
  the trajectory format ``bench.py --metrics-out`` writes and
  ``tools/check_telemetry_schema.py`` validates (committed schema:
  ``tools/telemetry_schema.json`` — drift fails tier-1);
- :func:`health` — one at-a-glance JSON snapshot (live_ranks,
  generation, frontier_lag, residue, last durable WAL watermark, the
  loud-failure counters, the flight recorder's correlation key) — the
  ``/healthz`` shape.

The flight recorder's postmortem artifact is its own sink
(``crdt_tpu.obs.FlightRecorder.dump`` — rendered and audited by
``tools/obs_report.py``); its records validate through the same
committed schema.
"""

from __future__ import annotations

import json
import re
import time
from typing import Any, Dict, Iterable, Optional

from .telemetry import (
    HIST_FIELDS, Telemetry, drain_events, is_concrete, to_dict,
)
from .utils.metrics import metrics

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """A Prometheus-legal metric name (dots and other punctuation to
    underscores; leading digit guarded)."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def prometheus_text(
    snapshot: Optional[Dict[str, Any]] = None,
    telemetry: Optional[Dict[str, Telemetry]] = None,
) -> str:
    """Render a registry snapshot (default: the live global registry)
    plus optional per-kind Telemetry pytrees as Prometheus text
    exposition. Counters become ``counter`` series; each gauge becomes
    ``<name>`` (last) plus ``_min``/``_max``/``_sum``/``_count``
    series; Telemetry fields land under
    ``crdt_tpu_telemetry_<field>{kind="..."}``."""
    snap = metrics.snapshot() if snapshot is None else snapshot
    lines = []
    for name, value in sorted(snap.get("counters", {}).items()):
        pname = sanitize(name)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {value}")
    for name, g in sorted(snap.get("gauges", {}).items()):
        pname = sanitize(name)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {g['last']}")
        for stat in ("min", "max", "sum"):
            lines.append(f"{pname}_{stat} {g[stat]}")
        lines.append(f"{pname}_count {g['n']}")
    # Field-major: ONE # TYPE block per metric with every {kind=...}
    # sample grouped under it — a second TYPE line for the same metric
    # is invalid exposition and fails the whole scrape.
    tels = {
        kind: to_dict(tel)
        for kind, tel in sorted((telemetry or {}).items())
        if is_concrete(tel)
    }
    for field in Telemetry._fields:
        if not tels:
            break
        pname = f"crdt_tpu_telemetry_{sanitize(field)}"
        if field in HIST_FIELDS:
            # Conformant Prometheus histogram exposition: CUMULATIVE
            # `le`-labeled buckets ending at +Inf (whose sample equals
            # `_count`), an exact `_sum`, one TYPE block per metric.
            lines.append(f"# TYPE {pname} histogram")
            for kind, d in tels.items():
                label = json.dumps(kind)
                h = d[field]
                cum = 0
                for edge, c in zip(h["edges"] + ["+Inf"], h["counts"]):
                    cum += c
                    le = json.dumps(_le(edge))
                    lines.append(
                        f"{pname}_bucket{{kind={label},le={le}}} {cum}"
                    )
                lines.append(f"{pname}_sum{{kind={label}}} {h['total']}")
                lines.append(f"{pname}_count{{kind={label}}} {cum}")
            continue
        lines.append(f"# TYPE {pname} gauge")
        for kind, d in tels.items():
            label = json.dumps(kind)  # quote + escape
            lines.append(f"{pname}{{kind={label}}} {d[field]}")
    return "\n".join(lines) + "\n"


def _le(edge) -> str:
    """Prometheus `le` label text for one bucket upper edge: integral
    edges print without a trailing ``.0`` (the canonical exposition
    form), the unbounded bucket is the literal ``+Inf``."""
    if edge == "+Inf":
        return "+Inf"
    f = float(edge)
    return str(int(f)) if f == int(f) else repr(f)


def write_prometheus(path: str, **kw) -> None:
    """``prometheus_text`` to a file (textfile-collector handoff)."""
    with open(path, "w") as f:
        f.write(prometheus_text(**kw))


def snapshot_record(snapshot: Optional[Dict[str, Any]] = None) -> dict:
    snap = metrics.snapshot() if snapshot is None else snapshot
    return {
        "record": "snapshot",
        "ts": time.time(),
        "counters": snap.get("counters", {}),
        "gauges": snap.get("gauges", {}),
    }


def telemetry_record(kind: str, tel: Telemetry) -> dict:
    """One JSONL line for a concrete Telemetry pytree."""
    return {"record": "telemetry", "ts": time.time(), "kind": kind,
            **to_dict(tel)}


def drain_jsonl(
    path: str,
    snapshot: Optional[Dict[str, Any]] = None,
    telemetry: Optional[Dict[str, Telemetry]] = None,
    spans: Optional[Iterable[dict]] = None,
) -> int:
    """Append one snapshot record, every concrete Telemetry record, and
    the span events (default: drain the telemetry.span buffer) to
    ``path``. Returns the number of lines written. Every line conforms
    to ``tools/telemetry_schema.json``."""
    written = 0
    with open(path, "a") as f:
        # Drain the span ring only AFTER the sink opened: an unwritable
        # path must not destroy the buffered events.
        records = [snapshot_record(snapshot)]
        for kind, tel in sorted((telemetry or {}).items()):
            if is_concrete(tel):
                records.append(telemetry_record(kind, tel))
        records.extend(drain_events() if spans is None else spans)
        for rec in records:
            try:
                # default=str: span attrs may carry numpy/jnp scalars —
                # one bad event must not abort the whole drain.
                line = json.dumps(rec, default=str)
            except (TypeError, ValueError):
                continue
            f.write(line + "\n")
            written += 1
    return written


def _federation_block(counters, gauges, worst) -> Dict[str, Any]:
    """The geo-federation vitals (crdt_tpu/geo/, ISSUE 20): every
    field is the ``-1`` sentinel until the FIRST cross-region exchange
    lands — a dashboard can tell "single-mesh deployment" apart from
    "federated but silent" at a glance."""
    exchanges = int(counters.get("geo.exchanges", 0)) or int(sum(
        v for name, v in counters.items()
        if name.endswith(".geo.exchanges")
    ))
    if exchanges <= 0:
        return {
            "regions_live": -1,
            "home_tenants": -1,
            "cross_region_bytes": -1,
            "watermark_lag_p99": -1.0,
            "failovers": -1,
        }
    bytes_ = int(counters.get("geo.exchange_bytes", 0)) or int(sum(
        v for name, v in counters.items()
        if name.endswith(".geo.exchange_bytes")
    ))
    lag_vals = [
        g["last"] for name, g in gauges.items()
        if name.endswith(".hist.geo_watermark_lag.p99")
    ]
    return {
        "regions_live": int(worst(".regions_live")),
        "home_tenants": int(worst(".geo_home_tenants")),
        "cross_region_bytes": bytes_,
        "watermark_lag_p99": (
            float(max(lag_vals)) if lag_vals else -1.0
        ),
        "failovers": int(counters.get("geo.failovers", 0)),
    }


def health(snapshot: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One at-a-glance mesh health snapshot (the ``/healthz`` shape),
    derived from the live registry (or an explicit snapshot) plus the
    installed flight recorder:

    - ``live_ranks`` / ``generation`` — the scale-out gauges (PR 11;
      ``live_ranks`` falls back to the max per-kind telemetry gauge
      when no ScaleoutMesh ever ran);
    - ``frontier_lag`` / ``residue`` — worst last-observed value over
      every per-kind telemetry gauge (0 = certified-stable mesh);
    - ``last_durable_watermark`` — the newest fsynced WAL seq
      (``durability.wal.watermark``; -1 = nothing durable yet);
    - ``faults_gave_up`` / ``snapshot_fallbacks`` — the loud-failure
      counters worth paging on;
    - ``serving`` — the serving-tier vitals: served tenant population
      and live subscribers (worst per-kind telemetry gauge), ingest
      backpressure refusals, fan-out resync fallbacks, the pipelined
      loop's durability and overlap totals (serve-WAL bytes, overlap
      hits, rebalance moves — ISSUE 18), and the newest end-to-end
      freshness p99 (µs; -1 until a sampled trace completes —
      crdt_tpu/obs/trace.py);
    - ``federation`` — the geo-federation vitals (ISSUE 20): live
      regions, home-tenant count, cross-region δ wire bytes, the
      worst per-read mirror watermark-lag p99, and region failovers —
      every field ``-1`` until the first cross-region exchange lands;
    - ``flight`` — the recorder's correlation key + buffered/dropped
      event counts (null when none is installed).

    Everything is plain JSON — serve it, log it, or diff it in an
    incident channel."""
    from .obs import get_recorder

    snap = metrics.snapshot() if snapshot is None else snapshot
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})

    def last(name: str, default: float = 0.0) -> float:
        g = gauges.get(name)
        return g["last"] if g else default

    def worst(suffix: str) -> float:
        vals = [
            g["last"] for name, g in gauges.items()
            if name.endswith(suffix)
        ]
        return max(vals) if vals else 0.0

    live = last("scaleout.live_ranks", -1.0)
    if live < 0:
        live = worst(".live_ranks")
    rec = get_recorder()
    return {
        "ts": time.time(),
        "live_ranks": int(live),
        "generation": int(last("scaleout.generation")),
        "frontier_lag": int(worst(".frontier_lag")),
        "residue": int(worst(".residue")),
        "last_durable_watermark": int(
            last("durability.wal.watermark", -1.0)
        ),
        "faults_gave_up": int(counters.get("faults.gave_up", 0)),
        "snapshot_fallbacks": int(
            counters.get("durability.snapshot_fallback", 0)
        ),
        "serving": {
            "live_tenants": int(worst(".live_tenants")),
            "subscribers_live": int(worst(".subscribers_live")),
            "ingest_backpressure": int(
                counters.get("serve.ingest.backpressure", 0)
            ),
            "resync_fallbacks": int(sum(
                v for name, v in counters.items()
                if name.endswith(".fanout.resync_fallbacks")
            )),
            "serve_wal_bytes": int(sum(
                v for name, v in counters.items()
                if name.endswith(".serve.wal_bytes")
            )),
            "overlap_hits": int(sum(
                v for name, v in counters.items()
                if name.endswith(".serve.overlap_hit")
            )),
            "rebalance_moves": int(sum(
                v for name, v in counters.items()
                if name.endswith(".serve.rebalance_moves")
            )),
            "freshness_p99_us": float(
                last("obs.trace.freshness_p99_us", -1.0)
            ),
        },
        "federation": _federation_block(counters, gauges, worst),
        "flight": None if rec is None else {
            "key": list(rec.key()),
            "events": len(rec),
            "dropped": rec.dropped,
        },
    }


__all__ = [
    "drain_jsonl", "health", "prometheus_text", "sanitize",
    "snapshot_record", "telemetry_record", "write_prometheus",
]
