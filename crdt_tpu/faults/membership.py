"""Rank liveness and eviction — the membership half of fault tolerance.

The mesh's safe default for a silent rank is to WAIT: PR 5's stable
frontier pins on a straggler's stale top, which is never unsafe but
lets memory grow without bound exactly when a production mesh is
degraded. This module is the operator-side escape hatch: per-rank miss
accounting fed by the in-kernel :class:`~.inject.FaultCounters` streaks,
a K-consecutive-misses suspicion rule, and an explicit eviction decision
that (a) rebuilds the ring permutation over live ranks only
(``inject.ring_perm`` — still a true bijection, so the PR 7 collective
lint holds) and (b) removes the evicted rank's top from the frontier
``pmin``, unpinning reclamation.

Protocol (the chaos tests and ``bench.py --chaos`` walk it end to end):

1. run mesh rounds with ``faults=tracker.plan(base)``;
2. feed the returned counters to :meth:`Membership.observe` — a rank
   whose outbound link delivered nothing for ``k_suspect`` consecutive
   rounds becomes SUSPECT;
3. :meth:`Membership.evict` suspects (policy: automatic via
   ``auto_evict=True`` on observe, or operator-driven);
4. a returning rank calls :meth:`Membership.rejoin` ONLY after
   state-driven resync (Enes et al. 1803.02750) — while it was out,
   the frontier may have advanced past its top and compaction may have
   retired parked slots it never saw, so δ re-entry from its stale
   tracking is forbidden. THREE sound re-entry paths:

   - **full-state resync** (the original contract — always available):
     the rank's state is replaced wholesale by full-state gossip/fold
     over a live replica; ships a whole state, needs no local
     artifacts.
   - **log-suffix rejoin** (ISSUE 10,
     ``crdt_tpu.durability.recover.rejoin``) for a rank that recovered
     locally from its snapshot + write-ahead δ-log: the live peer
     ships only its join-irreducible decomposition over the recovered
     state (reconstruction is positionally bit-exact whatever the
     bound, and the final join keeps recovered-but-unreplicated local
     content) — < 25% of full-state bytes on the ``bench.py
     --recovery`` gate.
   - **bootstrap-from-⊥** (ISSUE 11, ``crdt_tpu.scaleout.bootstrap``):
     the rank re-enters as a NEW member through the scale-out admit
     path — its causal lower bound is ⊥ (or a PR 10 snapshot as the
     warm base, which again ships only the log suffix), the wire
     carries segmented, integrity-checked ``decompose(live, base)``
     lanes, and its pre-eviction identity (tracking, marks, window
     state) is simply abandoned. This is the right exit when the
     rank's local artifacts are gone or untrusted; membership-wise it
     is ``ScaleoutMesh.admit``, not ``rejoin``.
   - **inter-mesh re-homing** (ISSUE 20, ``crdt_tpu.geo.failover
     .fail_over_region``) — the FOURTH contract, one level up: here
     the evicted member is a whole REGION (one mesh), and what
     re-enters is not the region but its HOME TENANT SHARDS, re-homed
     onto the surviving regions by minimal rendezvous remap. Each new
     home rebuilds a tenant from the dead region's durable tier
     (snapshot rows + the ServeWal suffix replayed through its own
     ingest queue — acks were gated on that WAL's group commit, so a
     complete tier recovers every acked op) plus peer-region
     divergence lanes (surviving mirrors, δ-decomposed against the
     recovery; adopted wholesale only in the sole-survivor case).
     Membership-wise it is ``FederationMembership.evict`` — a
     generation bump that refuses every pre-failover packet — and
     every ack window touching a re-homed tenant resets to ⊥ with its
     surviving mirrors cleared, so the next cross-region exchange
     re-ships full state against ⊥.

   δ re-entry from stale marks remains forbidden on every path —
   intra-mesh (rank tracking, ack marks) and inter-mesh (geo link
   acked bases) alike.

The liveness signal is receiver-measured: device p's ``miss_streak[p]``
counts consecutive end-of-run rounds with nothing arriving on its
inbound link, and :meth:`observe` maps that back to the SENDER through
the same ``sender_of`` table the kernel used. Streaks that span runs
accumulate (a run fully missed extends the streak by its round count);
any delivery resets.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..utils.metrics import metrics
from .inject import FaultPlan, ring_perm, sender_of


def validate_perm(perm: Sequence[Tuple[int, int]], p: int) -> List[str]:
    """Check a ppermute pair list is a TRUE BIJECTION of a size-``p``
    axis — every rank sends exactly once and receives exactly once.
    Returns the violations (empty = valid). This is the standalone
    detector behind the ``faults`` static-check section: the broken
    eviction twin (``analysis.fixtures.eviction_drops_ranks``, which
    omits evicted ranks instead of self-looping them) must fail here,
    exactly as it would fail the PR 7 ppermute lint once traced."""
    errs: List[str] = []
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    for name, seen in (("source", srcs), ("destination", dsts)):
        missing = sorted(set(range(p)) - set(seen))
        dupes = sorted({x for x in seen if seen.count(x) > 1})
        if missing:
            errs.append(f"{name}s missing ranks {missing} (axis size {p})")
        if dupes:
            errs.append(f"duplicate {name}s {dupes}")
    out_of_range = sorted(
        {x for x in srcs + dsts if not 0 <= x < p}
    )
    if out_of_range:
        errs.append(f"ranks {out_of_range} outside axis [0, {p})")
    return errs


class Membership:
    """Host-side liveness tracker for one replica mesh axis."""

    def __init__(self, n_ranks: int, k_suspect: int = 3):
        if n_ranks < 1:
            raise ValueError("n_ranks must be >= 1")
        if k_suspect < 1:
            raise ValueError("k_suspect must be >= 1")
        self.n_ranks = n_ranks
        self.k_suspect = k_suspect
        # Consecutive missed rounds per SENDER rank (accumulated across
        # runs; reset by any observed delivery or by rejoin).
        self.streaks = [0] * n_ranks
        self._evicted: set = set()

    # ---- state ------------------------------------------------------------

    @property
    def evicted(self) -> Tuple[int, ...]:
        return tuple(sorted(self._evicted))

    def live(self) -> Tuple[int, ...]:
        return tuple(
            i for i in range(self.n_ranks) if i not in self._evicted
        )

    def suspects(self) -> Tuple[int, ...]:
        """Live ranks whose outbound link has missed ``k_suspect``
        consecutive rounds."""
        return tuple(
            i for i in range(self.n_ranks)
            if i not in self._evicted and self.streaks[i] >= self.k_suspect
        )

    def plan(self, base: Optional[FaultPlan] = None) -> FaultPlan:
        """The base plan with this tracker's current eviction set — what
        the next mesh round should run under."""
        return (base or FaultPlan()).with_evicted(self.evicted)

    # ---- transitions ------------------------------------------------------

    def observe(self, counters, rounds: int,
                auto_evict: bool = False) -> Tuple[int, ...]:
        """Fold one run's :class:`~.inject.FaultCounters` in. ``rounds``
        is the run's exchange-round count (the in-kernel streak
        saturates there — a fully-missed run extends a spanning streak
        rather than resetting it). Returns the post-update suspect set;
        with ``auto_evict=True`` suspects are evicted immediately."""
        streak = np.asarray(counters.miss_streak).reshape(-1)
        if streak.shape[0] != self.n_ranks:
            raise ValueError(
                f"miss_streak has {streak.shape[0]} lanes, tracker "
                f"covers {self.n_ranks} ranks"
            )
        senders = sender_of(self.n_ranks, self.evicted)
        for dst in range(self.n_ranks):
            src = senders[dst]
            if src == dst and src in self._evicted:
                continue  # self-loop of an evicted rank: no liveness info
            s = int(streak[dst])
            if s >= rounds > 0:
                self.streaks[src] += rounds  # whole run missed: spans
            else:
                self.streaks[src] = s
        hot = self.suspects()
        for r in hot:
            metrics.count("faults.rank_suspected")
            obs.emit("rank_suspected", suspect=r, streak=self.streaks[r])
        if auto_evict:
            for r in hot:
                self.evict(r)
        return hot

    def evict(self, rank: int) -> None:
        """Remove ``rank`` from the ring and the frontier ``pmin``. The
        headline consequence: the mesh's stable frontier stops pinning
        on the dead rank's stale top and reclamation resumes
        (reclaim/frontier.py documents why the un-evicted default must
        pin)."""
        self._check_rank(rank)
        if rank in self._evicted:
            return
        if len(self._evicted) + 1 >= self.n_ranks:
            raise ValueError(
                f"evicting rank {rank} would leave fewer than one live "
                f"rank on a {self.n_ranks}-rank axis"
            )
        self._evicted.add(rank)
        metrics.count("faults.rank_evicted")
        obs.emit("rank_evicted", evicted=rank,
                 live=self.n_ranks - len(self._evicted))

    def rejoin(self, rank: int) -> None:
        """Re-admit ``rank``. PRECONDITION (the caller's contract): the
        rank's state has been replaced by state-driven resync against a
        live replica — full-state gossip, or the log-suffix form
        (``durability.recover.rejoin``) when the rank recovered locally
        from snapshot + WAL (module docstring item 4). Its pre-eviction
        δ TRACKING is stale either way (the frontier may have advanced
        past its top; compaction may have retired slots it never saw)
        and must not re-enter the δ ring; a state join is always sound,
        δ re-entry from stale marks is not."""
        self._check_rank(rank)
        self._evicted.discard(rank)
        self.streaks[rank] = 0
        metrics.count("faults.rank_rejoined")
        obs.emit("rank_rejoined", rejoined=rank,
                 live=self.n_ranks - len(self._evicted))

    def _check_rank(self, rank: int) -> None:
        if not 0 <= rank < self.n_ranks:
            raise ValueError(
                f"rank {rank} outside [0, {self.n_ranks})"
            )

    def ring(self) -> List[Tuple[int, int]]:
        """The current live-rank ring permutation (a true bijection)."""
        return ring_perm(self.n_ranks, self.evicted)


# Flight-recorder event schemas for the membership transitions
# (registration is the coverage contract — obs/recorder.py).
from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev("rank_suspected", subsystem="faults.membership",
        fields=("suspect", "streak"), module=__name__)
_reg_ev("rank_evicted", subsystem="faults.membership",
        fields=("evicted", "live"), module=__name__)
_reg_ev("rank_rejoined", subsystem="faults.membership",
        fields=("rejoined", "live"), module=__name__)


__all__ = ["Membership", "validate_perm"]
