"""Host-side DCN resilience: timeout + exponential backoff with jitter.

The in-kernel fault machinery (inject/integrity/membership) covers the
ICI mesh; the OTHER network — DCN between hosts, where
``multihost.sync_list`` and ``multihost._allgather_host`` live — fails
in host-visible ways (coordinator hiccups, a slow peer, a transient
gloo error) and previously had zero retry/timeout/backoff: one blip
took the whole exchange down. This module is the standard remedy,
CRDT-flavored: because every exchange is an idempotent lattice join (or
an idempotent op re-ingest keyed by globally-unique identifiers),
RETRYING A WHOLE EXCHANGE IS ALWAYS SAFE — re-delivery is absorbed, so
the policy can be aggressive without an exactly-once protocol.

``with_retries`` wraps one exchange attempt; on exhaustion it raises
:class:`DcnExchangeFailed` CARRYING THE LAST-GOOD STATE (the watermark
/ array the caller should resume from), so a failed sync degrades to
"retry later from here", never to lost progress. Counters:
``faults.retries`` (re-attempts), ``faults.timeouts`` (attempts that
hit the per-attempt deadline), ``faults.gave_up`` (exchanges abandoned).

CAVEATS, stated plainly: a timed-out attempt's worker thread cannot be
killed — it is abandoned as a daemon thread and may still complete in
the background, holding its resources until it returns. For that
reason the per-attempt ``timeout`` is ONLY safe around exchanges whose
late completion cannot interleave with the retry — a plain RPC, a
blob fetch. It is NOT safe around collectives: an abandoned attempt's
in-flight allgather can pair with the retry's fresh allgather on peer
processes, mispairing rounds cluster-wide — so the multihost wrappers
(``sync_list``/``_allgather_host``) REFUSE a policy with a timeout.
And retries of a collective exchange must be symmetric across
processes (every process re-enters with the same policy) or the
survivors deadlock waiting on the giver-upper — pick ``attempts``
uniformly from config, not per-call. Symmetry of the POLICY is not
symmetry of the FAILURE: a transient error raised on one process while
its peers' matching collectives succeeded leaves the retrier out of
step, and its restarted collectives can pair with the peers' later
ones — for a multi-collective exchange that is silent corruption, not
deadlock. ``multihost.sync_list`` therefore opens every retried
attempt with an attempt-number lockstep check that turns the mispair
into a loud ``DcnExchangeFailed``; wrap other multi-collective
exchanges the same way.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from .. import obs
from ..utils.metrics import metrics


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff shape for one exchange. ``base_delay`` doubles (times
    ``backoff``) per retry up to ``max_delay``; each sleep is scaled by
    ``1 + U(0, jitter)`` so herds decorrelate; ``timeout`` is the
    per-ATTEMPT deadline in seconds (None = wait forever); ``seed``
    makes the jitter deterministic (tests)."""

    attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    backoff: float = 2.0
    jitter: float = 0.5
    timeout: Optional[float] = None
    seed: Optional[int] = None

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError("attempts must be >= 1")
        if self.backoff < 1.0:
            raise ValueError("backoff must be >= 1.0")


DEFAULT_POLICY = RetryPolicy()


class DcnExchangeFailed(RuntimeError):
    """A DCN exchange exhausted its retry budget. ``last_good`` is the
    resume point the caller handed in (e.g. ``sync_list``'s watermark:
    ops below it are already everywhere; re-sync later ``since`` it);
    ``cause`` the final attempt's exception."""

    def __init__(self, op: str, attempts: int, cause: BaseException,
                 last_good: Any = None):
        super().__init__(
            f"DCN exchange '{op}' failed after {attempts} attempt(s): "
            f"{type(cause).__name__}: {cause} — resume from last_good"
        )
        self.op = op
        self.attempts = attempts
        self.cause = cause
        self.last_good = last_good


class _AttemptTimeout(RuntimeError):
    pass


def _call_with_timeout(fn: Callable[[], Any], timeout: Optional[float],
                       op: str) -> Any:
    if timeout is None:
        return fn()
    box: dict = {}

    def runner():
        try:
            box["result"] = fn()
        except BaseException as exc:  # re-raised on the caller thread
            box["error"] = exc

    t = threading.Thread(
        target=runner, name=f"dcn-{op}", daemon=True
    )
    t.start()
    t.join(timeout)
    if t.is_alive():
        # The thread is abandoned (see the module caveat) — safe only
        # because every exchange is idempotent.
        metrics.count("faults.timeouts")
        raise _AttemptTimeout(
            f"'{op}' attempt exceeded {timeout}s"
        )
    if "error" in box:
        raise box["error"]
    return box["result"]


def with_retries(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    *,
    op: str = "dcn",
    last_good: Any = None,
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Run one idempotent exchange under ``policy``. Returns ``fn()``'s
    value; raises :class:`DcnExchangeFailed` (carrying ``last_good``)
    after the final attempt. ``sleep`` is injectable for tests."""
    policy = policy or DEFAULT_POLICY
    rng = random.Random(policy.seed)
    delay = policy.base_delay
    last_exc: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        if attempt:
            metrics.count("faults.retries")
            obs.emit("dcn_retry", op=op, attempt=attempt,
                     error=type(last_exc).__name__)
            pause = min(delay, policy.max_delay)
            pause *= 1.0 + policy.jitter * rng.random()
            sleep(pause)
            delay *= policy.backoff
        try:
            return _call_with_timeout(fn, policy.timeout, op)
        except DcnExchangeFailed:
            raise  # a nested wrapped exchange already gave up
        except (KeyboardInterrupt, SystemExit):
            raise  # an operator abort must never be retried into
        except Exception as exc:
            last_exc = exc
    metrics.count("faults.gave_up")
    assert last_exc is not None
    # The postmortem boundary: record the exhaustion and write the
    # flight-recorder artifact BEFORE raising (obs/recorder.py —
    # auto_dump never masks the exception it documents).
    obs.emit("dcn_exchange_failed", op=op, attempts=policy.attempts,
             error=type(last_exc).__name__)
    obs.auto_dump("dcn_exchange_failed", op=op)
    raise DcnExchangeFailed(
        op, policy.attempts, last_exc, last_good=last_good
    ) from last_exc


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev("dcn_retry", subsystem="faults.retry",
        fields=("op", "attempt", "error"), module=__name__)
_reg_ev("dcn_exchange_failed", subsystem="faults.retry",
        fields=("op", "attempts", "error"), module=__name__)


from ..analysis.registry import register_effect_source as _reg_src  # noqa: E402

# The per-attempt timeout watchdog thread (_call_with_timeout) is the
# only thread crdt_tpu spawns; the concurrency section's thread lint
# requires every threading.Thread site to live in a registered effect
# source's module — daemon, named, and declared here.
_reg_src(
    "retry.dcn_watchdog", module=__name__,
    description="daemon thread bounding one DCN exchange attempt; "
    "touches no registered shared field (result lands in a local box)",
)

__all__ = [
    "DEFAULT_POLICY", "DcnExchangeFailed", "RetryPolicy", "with_retries",
]
