"""Shared fault-scenario generators (SURVEY §6.3 delivery contract).

These were minted inside tests/test_fault_injection.py; the chaos-soak
suite and ``bench.py --chaos`` need the SAME schedule semantics, so the
generators live here once instead of drifting as copies. The delivery
contract they encode:

- per-origin causal order is preserved (each site's own op stream is
  delivered as a prefix — dropping is always a SUFFIX drop),
- cross-site order is free (arbitrary interleaving),
- duplication is unbounded (CmRDT apply must be idempotent on dups).

Deterministic given the caller's ``random.Random`` — chaos runs replay.
"""

from __future__ import annotations

import random
from typing import List, Tuple

MEMBERS = list(range(5))


def mint_streams(rng: random.Random, n_sites: int, n_ops: int,
                 members=None) -> Tuple[list, List[list]]:
    """Per-site op streams minted under each site's own actor (per-origin
    causal order is the delivery contract; cross-site order is free).
    Returns ``(sites, streams)`` — the pure replicas after self-applying
    their own ops, and each site's op list."""
    from ..pure.orswot import Orswot

    members = MEMBERS if members is None else members
    sites = [Orswot() for _ in range(n_sites)]
    streams: List[list] = [[] for _ in range(n_sites)]
    for _ in range(n_ops):
        i = rng.randrange(n_sites)
        s = sites[i]
        if rng.random() < 0.7 or not s.read().val:
            op = s.add(rng.choice(members), s.read().derive_add_ctx(f"s{i}"))
        else:
            victim = rng.choice(sorted(s.read().val))
            op = s.rm(victim, s.contains(victim).derive_rm_ctx())
        s.apply(op)
        streams[i].append(op)
    return sites, streams


def genesis_tracking(state):
    """δ-tracking (dirty, fctx) for a dense ORSWOT batch whose replicas
    were last mutually synced at GENESIS — every live row marked dirty
    with its own dots as context (``interval_accumulate`` from the
    all-zero state). The bootstrap every chaos/scale-out scenario run
    starts from; lived as per-file closure copies until ISSUE 11."""
    import jax
    import jax.numpy as jnp

    from ..parallel.delta import interval_accumulate

    zero = jax.tree.map(jnp.zeros_like, state)
    dirty = jnp.zeros(state.ctr.shape[:-1], bool)
    fctx = jnp.zeros(state.ctr.shape, state.ctr.dtype)
    return interval_accumulate(dirty, fctx, zero, state)


def faulty_delivery(rng: random.Random, streams: List[list],
                    r_ix: int) -> list:
    """One receiver's faulty delivery schedule:

    - DROP a suffix of each foreign stream (prefix delivery is the
      causal contract);
    - DUPLICATE random ops (CmRDT apply must be idempotent on dups);
    - REORDER across sites (interleave streams arbitrarily, each
      stream's own order preserved)."""
    plan = []
    for s_ix, stream in enumerate(streams):
        if s_ix == r_ix:
            continue
        keep = rng.randint(0, len(stream))  # drop a suffix
        prefix = stream[:keep]
        dups = [op for op in prefix if rng.random() < 0.3]
        plan.append(prefix + dups)
    merged, cursors = [], [0] * len(plan)
    while any(c < len(p) for c, p in zip(cursors, plan)):
        choices = [
            i for i, (c, p) in enumerate(zip(cursors, plan)) if c < len(p)
        ]
        i = rng.choice(choices)
        merged.append(plan[i][cursors[i]])
        cursors[i] += 1
    return merged


__all__ = [
    "MEMBERS", "faulty_delivery", "genesis_tracking", "mint_streams",
]
