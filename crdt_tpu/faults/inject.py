"""Seeded, jit-compatible fault injection for the device mesh.

SURVEY §6.3 makes fault-injection convergence the recovery story, but
until this module every fault lived in host-side test code while the
mesh itself assumed perfect links and immortal ranks. A
:class:`FaultPlan` moves the faults INTO the traced program: per-round
× per-link drop / corrupt / delay decisions are minted from
``jax.random`` inside the kernel (keyed on ``(seed, round, rank)``), so
a chaos run is deterministic, replayable, and exercises the REAL
compiled exchange — the same ppermutes, the same apply kernels — not a
host-side simulation of them.

The plan is a frozen, hashable dataclass: it rides the jit-cache key
(``anti_entropy._cached``), and ``faults=None`` (the default) traces
NOTHING — the flag-off program is byte-identical to the pre-flag one,
pinned by HLO-equality tests exactly like ``telemetry=`` /
``stability=``.

Fault semantics (per inbound link, per round):

- **drop** — the packet never arrives; the receiver keeps local state.
- **corrupt** — the payload is perturbed ON THE WIRE (after the
  sender's checksum — faults/integrity.py); the receiver's verify
  fails and it REJECTS: same outcome as a drop, counted separately
  (``packets_rejected``). Corrupted content is never joined.
- **delay** — the link holds the packet one round; it arrives (and is
  applied) on the next round, or in the ring epilogue if the loop ends
  first. Nothing is lost, only late.
- **dead ranks** (``dead=``) — every packet FROM those ranks drops:
  the crash-fault a liveness tracker (faults/membership.py) detects
  via the per-receiver miss streaks.
- **evicted ranks** (``evicted=``) — membership's decision applied:
  the ring permutation is rebuilt over live ranks only
  (:func:`ring_perm` — still a true bijection of the full axis, so the
  collective-semantics lint holds; evicted ranks self-loop), and the
  stable-frontier ``pmin`` excludes evicted tops, UNPINNING
  reclamation (reclaim/frontier.py's straggler-pins rule is the safe
  default; eviction is the operator's explicit override). A rank
  evicted while holding unique knowledge must re-enter via FULL-STATE
  state-driven resync (Enes et al. 1803.02750) — never the δ ring —
  because stability may have been claimed past its top while it was
  out.

Lost packets void the δ-ring residue certificate: the ring forces
``residue >= 1`` whenever anything was dropped or rejected, so a
faulted run can never be mistaken for a certified-converged one.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from typing import NamedTuple

from ..utils.metrics import metrics


@dataclass(frozen=True)
class FaultPlan:
    """One degraded-mesh scenario (hashable: rides the jit-cache key).

    ``drop`` / ``corrupt`` / ``delay`` are per-link per-round
    probabilities in [0, 1]; ``seed`` keys the in-kernel draws; ``dead``
    ranks always drop outbound packets; ``evicted`` ranks are out of
    the ring and the frontier (see the module docstring)."""

    seed: int = 0
    drop: float = 0.0
    corrupt: float = 0.0
    delay: float = 0.0
    dead: Tuple[int, ...] = ()
    evicted: Tuple[int, ...] = ()

    def __post_init__(self):
        for name in ("drop", "corrupt", "delay"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"FaultPlan.{name}={v} not in [0, 1]")
        object.__setattr__(self, "dead", tuple(sorted(self.dead)))
        object.__setattr__(self, "evicted", tuple(sorted(self.evicted)))

    def with_evicted(self, evicted) -> "FaultPlan":
        return replace(self, evicted=tuple(sorted(evicted)))


class FaultCounters(NamedTuple):
    """Per-run fault accounting (a pytree — returned traced under an
    outer jit, concrete otherwise). The scalar counters are mesh-wide
    sums; ``miss_streak[P]`` is per RECEIVER: consecutive rounds at the
    end of the run in which rank p's inbound link delivered nothing
    (dropped or rejected) — the liveness signal
    ``membership.Membership.observe`` maps back to sender ranks."""

    packets_dropped: jax.Array   # uint32
    packets_rejected: jax.Array  # uint32
    packets_delayed: jax.Array   # uint32
    miss_streak: jax.Array       # int32 [P]


def counters_specs():
    """shard_map out_specs for :class:`FaultCounters` (scalars
    replicated, the streak sharded one lane per replica rank)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import REPLICA_AXIS

    return FaultCounters(P(), P(), P(), P(REPLICA_AXIS))


def combine_counters(a: FaultCounters, b: FaultCounters) -> FaultCounters:
    """Fold two runs' counters (elastic retry attempts): the packet
    counters add — they were real wire events — while the liveness
    streak comes from the LATER run (it describes where the links
    ended, not a rate)."""
    return FaultCounters(
        packets_dropped=a.packets_dropped + b.packets_dropped,
        packets_rejected=a.packets_rejected + b.packets_rejected,
        packets_delayed=a.packets_delayed + b.packets_delayed,
        miss_streak=b.miss_streak,
    )


def accumulate_counters(
    fcs: Optional[FaultCounters], counters: FaultCounters
) -> FaultCounters:
    """One elastic attempt's counters folded into the running total —
    the identity-seeding form both elastic wrappers share."""
    return counters if fcs is None else combine_counters(fcs, counters)


def is_concrete(fc: FaultCounters) -> bool:
    return not any(
        isinstance(x, jax.core.Tracer) for x in jax.tree.leaves(fc)
    )


def record(fc: FaultCounters) -> None:
    """Drain concrete counters into the host registry under the
    ``faults.*`` names (a no-op under tracing, like
    ``telemetry.record``), and emit one ``fault_counters`` flight event
    when a recorder is installed — the per-round drop/reject/delay
    entry on the postmortem timeline."""
    if not is_concrete(fc):
        return
    dropped = int(fc.packets_dropped)
    rejected = int(fc.packets_rejected)
    delayed = int(fc.packets_delayed)
    metrics.count("faults.packets_dropped", dropped)
    metrics.count("faults.packets_rejected", rejected)
    metrics.count("faults.packets_delayed", delayed)
    metrics.observe("faults.miss_streak", float(jnp.max(fc.miss_streak)))
    if dropped or rejected or delayed:
        from .. import obs

        obs.emit("fault_counters", dropped=dropped, rejected=rejected,
                 delayed=delayed)


# ---- ring permutations over live ranks ------------------------------------

def ring_perm(p: int, evicted: Tuple[int, ...] = ()) -> List[Tuple[int, int]]:
    """The δ/gossip ring permutation rebuilt over LIVE ranks: live rank
    i sends to the next live rank up-ring; evicted ranks self-loop.
    Always a true bijection of the full axis (the PR 7 ppermute lint's
    contract — ``membership.validate_perm`` is the standalone checker),
    so eviction changes who exchanges, never the collective's shape."""
    live = [i for i in range(p) if i not in set(evicted)]
    pairs = [(i, i) for i in range(p) if i not in live]
    pairs += [
        (live[i], live[(i + 1) % len(live)]) for i in range(len(live))
    ]
    return sorted(pairs)


def inv_ring_perm(
    p: int, evicted: Tuple[int, ...] = ()
) -> List[Tuple[int, int]]:
    """The inverse (down-ring) permutation — the digest exchange runs
    against the ring (delta_ring.py)."""
    return sorted((dst, src) for src, dst in ring_perm(p, evicted))


def sender_of(
    p: int, evicted: Tuple[int, ...] = ()
) -> List[int]:
    """``sender_of[dst] = src`` under :func:`ring_perm` — the static
    table a receiver indexes with its own rank to learn whose packets
    arrive on its inbound link (dead-rank drops, membership mapping)."""
    table = [0] * p
    for src, dst in ring_perm(p, evicted):
        table[dst] = src
    return table


# ---- in-kernel draws and perturbation -------------------------------------

def round_faults(plan: FaultPlan, r, axis_name: str, senders):
    """The inbound link's fault draws for mesh round ``r`` on the
    calling device (inside shard_map): returns scalar bools
    ``(dropped, corrupted, delayed)``. ``r`` may be a traced loop
    index; ``senders`` is the static :func:`sender_of` table for the
    active permutation. Mutually exclusive by priority drop > corrupt >
    delay (one packet suffers one fate per hop)."""
    rank = lax.axis_index(axis_name)
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(plan.seed), jnp.uint32(r)),
        rank,
    )
    u = jax.random.uniform(key, (3,))
    dropped = u[0] < plan.drop
    if plan.dead:
        src = jnp.asarray(senders, jnp.int32)[rank]
        dropped = dropped | jnp.isin(src, jnp.asarray(plan.dead, jnp.int32))
    corrupted = (u[1] < plan.corrupt) & ~dropped
    delayed = (u[2] < plan.delay) & ~dropped & ~corrupted
    return dropped, corrupted, delayed


def receive_wire(plan: FaultPlan, r, axis_name: str, senders,
                 payload, chk_in, delay_ok: bool = False):
    """The receiver side of one faulted link, shared by the δ ring and
    the gossip scaffold: draw this round's fates, MASK them on evicted
    receivers (a self-loop delivery is not a wire event — counting its
    draws would report phantom loss and void certificates for a run
    whose real links all delivered), corrupt the payload on the
    simulated wire, verify the checksum lane, and derive the keep mask.
    ``delay_ok=False`` (ring epilogue / no-delay plans) delivers a
    would-be-delayed payload now. Returns
    ``(payload, keep, (dropped, rejected, delayed))``."""
    from .integrity import verify

    dropped, corrupted, delayed = round_faults(plan, r, axis_name, senders)
    if plan.evicted:
        live = ~evicted_mask(plan, axis_name)
        dropped = dropped & live
        corrupted = corrupted & live
        delayed = delayed & live
    if not delay_ok:
        delayed = jnp.zeros((), bool)
    payload = corrupt_tree(payload, corrupted)
    ok = verify(payload, chk_in)
    rejected = ~ok & ~dropped
    keep = ~dropped & ~rejected & ~delayed
    return payload, keep, (dropped, rejected, delayed)


def tick_counters(fc, fates):
    """Fold one delivery's fates into the per-device counter carry
    ``(dropped u32, rejected u32, delayed u32, streak i32, *rest)`` —
    shared by both fault surfaces; trailing elements (the δ ring's
    ``lost`` lane) pass through for the caller to update."""
    dropped, rejected, delayed = fates
    lostq = dropped | rejected
    return (
        fc[0] + dropped.astype(jnp.uint32),
        fc[1] + rejected.astype(jnp.uint32),
        fc[2] + delayed.astype(jnp.uint32),
        jnp.where(lostq, fc[3] + 1, 0),  # end-of-run streak
    ) + tuple(fc[4:])


def block_wire(plan: FaultPlan, bix, payload):
    """The streaming fold's upload wire (parallel/stream.py): one
    drop/corrupt draw per block keyed ``(seed, block index)`` — same
    priority rule as :func:`round_faults` — corruption applied after
    the checksum, verify over what arrived. Returns ``(payload, code)``
    with the per-device fate code 0 = ok / 1 = dropped / 2 = rejected
    (the caller pmax-reduces it across the mesh). ``delay`` has no
    meaning on a host-ordered block stream and is ignored."""
    from .integrity import checksum, verify

    chk = checksum(payload)
    key = jax.random.fold_in(jax.random.PRNGKey(plan.seed), bix)
    u = jax.random.uniform(key, (2,))
    dropped = u[0] < plan.drop
    corrupted = (u[1] < plan.corrupt) & ~dropped
    payload = corrupt_tree(payload, corrupted)
    ok = verify(payload, chk)
    code = jnp.where(dropped, 1, jnp.where(~ok, 2, 0)).astype(jnp.int32)
    return payload, code


def corrupt_tree(tree, corrupted):
    """Perturb the payload's first lane when ``corrupted`` (the
    simulated wire flip): +1 on numeric leaves, a NOT on bools —
    exactly the class of perturbation ``integrity.checksum`` detects
    DETERMINISTICALLY, so a corrupted packet is always rejected, never
    joined. No-op (bit-identical) when ``corrupted`` is False."""

    def bump(leaf):
        flat = leaf.reshape(-1)
        if leaf.dtype == bool:
            poked = flat.at[0].set(flat[0] ^ corrupted)
        else:
            poked = flat.at[0].add(corrupted.astype(leaf.dtype))
        return poked.reshape(leaf.shape)

    leaves, treedef = jax.tree.flatten(tree)
    return jax.tree.unflatten(treedef, [bump(leaves[0])] + leaves[1:])


def tree_select(pred, on_true, on_false):
    """Leaf-wise ``jnp.where`` on a scalar predicate — how a receiver
    discards a dropped/rejected delivery without tracing a branch (the
    apply runs; its outputs are deselected)."""
    return jax.tree.map(
        lambda a, b: jnp.where(pred, a, b), on_true, on_false
    )


def evicted_mask(plan: Optional[FaultPlan], axis_name: str):
    """Scalar bool: is the calling device an evicted rank? (False when
    no plan or nothing evicted — callers guard with a Python ``if`` so
    the flag-off trace stays byte-identical.)"""
    if plan is None or not plan.evicted:
        return jnp.zeros((), bool)
    return jnp.isin(
        lax.axis_index(axis_name), jnp.asarray(plan.evicted, jnp.int32)
    )


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev("fault_counters", subsystem="faults",
        fields=("dropped", "rejected", "delayed"), module=__name__)


__all__ = [
    "FaultCounters", "FaultPlan", "accumulate_counters", "block_wire",
    "combine_counters", "corrupt_tree", "counters_specs",
    "evicted_mask", "inv_ring_perm", "is_concrete", "receive_wire",
    "record", "ring_perm", "round_faults", "sender_of",
    "tick_counters", "tree_select",
]
