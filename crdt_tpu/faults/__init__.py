"""crdt_tpu.faults — degraded-mesh fault tolerance.

Four cooperating pieces (see each module's docstring):

- :mod:`.inject` — seeded, jit-compatible fault injection: a
  :class:`FaultPlan` of per-round × per-link drop/corrupt/delay draws
  minted from ``jax.random`` INSIDE the traced program, accepted via a
  ``faults=`` flag on ``run_delta_ring``, the ``mesh_gossip*`` family,
  and ``mesh_stream_fold*`` (flag off = byte-identical pre-flag trace,
  the ``telemetry=`` discipline).
- :mod:`.integrity` — an in-kernel checksum lane on every shipped
  payload; mismatches REJECT (local state kept,
  ``faults.packets_rejected`` counted) and state-driven resync heals.
- :mod:`.membership` — rank liveness from the in-kernel miss streaks,
  K-consecutive-miss suspicion, eviction (ring rebuilt over live ranks,
  frontier pmin unpinned) and the full-state-resync rejoin contract.
- :mod:`.retry` — host-side DCN resilience: timeout + exponential
  backoff with jitter around ``multihost.sync_list`` /
  ``_allgather_host``, failing into :class:`DcnExchangeFailed` with
  the last-good resume state.

Plus :mod:`.scenarios` (the shared host-side fault-schedule generators
the test suites draw from) and :func:`static_checks` — the ``faults``
section of tools/run_static_checks.py: fault-surface registry coverage
and the broken-fixture detector gates.

**Healing a degraded run.** A lossy ring returns every rank's rows as
valid partial states with the certificate voided; two state-driven
resync modes re-converge them (both land bit-identical on the
fault-free fixpoint):

- full-state gossip over the returned rows (``mesh_gossip(rows,
  mesh)``) — no prerequisites, ships P whole states; the historical
  path and still the REJOIN contract for an evicted rank (its
  divergence has no usable lower bound);
- decomposition resync (:func:`resync`, re-exported from
  ``crdt_tpu.delta_opt.heal``) — each rank ships only its minimal
  irredundant join decomposition over a pre-divergence snapshot
  ``since`` (any mutually-known lower bound, e.g. the last certified
  fixpoint), so a partition that diverged by a handful of rows heals
  for a fraction of full-state bytes (``bench.py --heal`` measures
  the ratio; the reconstruction law pins exactness per kind).
"""

from __future__ import annotations

from typing import List

from .inject import (
    FaultCounters,
    FaultPlan,
    accumulate_counters,
    block_wire,
    combine_counters,
    corrupt_tree,
    counters_specs,
    evicted_mask,
    inv_ring_perm,
    receive_wire,
    record,
    ring_perm,
    round_faults,
    sender_of,
    tick_counters,
    tree_select,
)
from .integrity import checksum, checksum_detects, verify
from .membership import Membership, validate_perm
from .retry import DcnExchangeFailed, RetryPolicy, with_retries
from . import scenarios  # noqa: F401  (re-export the schedule generators)

# The bandwidth-optimal heal path (module docstring): decomposition
# resync lives in crdt_tpu/delta_opt/ (it is pure δ machinery), but the
# operator reaches for it from here, next to the fault plans that made
# it necessary.
from ..delta_opt.heal import ResyncReport, resync


def static_checks() -> List:
    """The ``faults`` static-check section (Finding list, empty =
    clean):

    1. **fault-surface coverage** — every public ``crdt_tpu.parallel``
       callable exposing a ``faults=`` parameter must have called
       ``analysis.registry.register_fault_surface``; an unregistered
       fault-capable entry fails discovery (the same
       registration-is-the-coverage-contract rule as joins/entries).
    2. **checksum detector** — ``integrity.checksum`` must detect every
       single-lane perturbation class the injector mints; the broken
       twin (``analysis.fixtures.checksum_ignores_corruption``) must
       FAIL the same detector — proving the gate fires.
    3. **eviction bijection** — ``inject.ring_perm`` must stay a true
       bijection for every eviction subset on the gate axis (and reduce
       to the standard ring when nothing is evicted); the broken twin
       (``analysis.fixtures.eviction_drops_ranks``) must fail
       ``membership.validate_perm``.
    """
    from ..analysis import fixtures
    from ..analysis.registry import unregistered_fault_surfaces
    from ..analysis.report import Finding

    findings: List[Finding] = []

    for name in unregistered_fault_surfaces():
        findings.append(Finding(
            "fault-surface-coverage", name,
            "public entry exposes a faults= parameter but never called "
            "register_fault_surface — the faults gate cannot see it",
        ))

    if not checksum_detects(checksum):
        findings.append(Finding(
            "checksum-detects", "integrity.checksum",
            "checksum failed to change under a single-lane perturbation "
            "— corrupted packets would be silently joined",
        ))
    if checksum_detects(fixtures.checksum_ignores_corruption):
        findings.append(Finding(
            "broken-fixture-missed", "checksum_ignores_corruption",
            "the corruption-blind checksum twin PASSED the detector — "
            "the integrity gate is not actually firing",
        ))

    p = 8
    for evicted in ((), (3,), (0, 5), tuple(range(1, p))):
        perm = ring_perm(p, evicted)
        errs = validate_perm(perm, p)
        if errs:
            findings.append(Finding(
                "eviction-bijection", f"ring_perm(p={p}, evicted={evicted})",
                "; ".join(errs),
            ))
    if ring_perm(p, ()) != sorted((i, (i + 1) % p) for i in range(p)):
        findings.append(Finding(
            "eviction-bijection", "ring_perm(p=8, evicted=())",
            "empty eviction set must reproduce the standard unit-shift "
            "ring exactly",
        ))
    if not validate_perm(fixtures.eviction_drops_ranks(p, (3,)), p):
        findings.append(Finding(
            "broken-fixture-missed", "eviction_drops_ranks",
            "the bijection-breaking eviction twin PASSED validate_perm — "
            "the membership gate is not actually firing",
        ))
    return findings


__all__ = [
    "DcnExchangeFailed", "FaultCounters", "FaultPlan", "Membership",
    "ResyncReport", "RetryPolicy", "accumulate_counters", "block_wire",
    "checksum", "checksum_detects", "combine_counters", "corrupt_tree",
    "counters_specs", "evicted_mask", "inv_ring_perm", "receive_wire",
    "record", "resync", "ring_perm", "round_faults", "scenarios",
    "sender_of", "static_checks", "tick_counters", "tree_select",
    "validate_perm", "verify", "with_retries",
]
