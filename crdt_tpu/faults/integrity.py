"""In-kernel link integrity: a checksum lane on every shipped payload.

The fault model (faults/inject.py) corrupts packets ON THE WIRE —
between the sender's extract and the receiver's apply. A receiver must
never join corrupted content (an undetected bit-flip in a dot clock is
a lattice-soundness violation, not just wrong data), so every shipped
pytree carries a checksum computed sender-side that travels the same
``ppermute``; the receiver recomputes over what actually arrived and
REJECTS on mismatch — local state kept, ``faults.packets_rejected``
counted, and the δ machinery's state-driven resync (Almeida et al.
1603.01529: δ anti-entropy tolerates message loss given eventual
resync) heals the gap.

The checksum is a position-weighted modular sum, not a cryptographic
hash: lane ``i`` of each leaf is weighted by the odd constant
``2*i + 1`` and leaf sums chain through multiplication by an odd
(hence invertible mod 2^32) mixing constant. Oddness is the detection
guarantee: any single-lane additive perturbation ``d`` changes the
digest by ``d * odd * odd^k`` — nonzero mod 2^32 whenever ``d`` is
(which covers every perturbation ``inject.corrupt_tree`` mints, and
any odd-delta flip in general) — so detection of the injected faults
is DETERMINISTIC, which is what lets the convergence tests assert
bit-identity rather than "converged with high probability". All lax
ops on static shapes: safe inside jit and shard_map, and cheap enough
(one pass over the packet) to ride every round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Invertible-mod-2^32 leaf chaining constant (odd; the golden-ratio
# mixing constant, same family as threefry's).
_MIX = 0x9E3779B1


def _lanes_u32(leaf: jax.Array) -> jax.Array:
    """A leaf's lanes as uint32 words, covering EVERY payload bit:
    floats bitcast (a 64-bit leaf becomes two u32 words — a low-mantissa
    flip must not vanish in a downcast), 8-byte integers likewise (a
    ``counter_dtype="uint64"`` clock's high bits are payload too),
    sub-4-byte lanes widen. No bit of the shipped content is outside
    the digest."""
    if leaf.dtype == jnp.bool_:
        return leaf.reshape(-1).astype(jnp.uint32)
    if leaf.dtype.itemsize > 4:
        # bitcast to a SMALLER itemsize appends a minor word axis —
        # both u32 halves of each lane enter the sum.
        return jax.lax.bitcast_convert_type(leaf, jnp.uint32).reshape(-1)
    if jnp.issubdtype(leaf.dtype, jnp.floating):
        if leaf.dtype.itemsize < 4:  # f16/bf16: bitcast, then widen
            return jax.lax.bitcast_convert_type(
                leaf, jnp.uint16
            ).reshape(-1).astype(jnp.uint32)
        return jax.lax.bitcast_convert_type(leaf, jnp.uint32).reshape(-1)
    return leaf.reshape(-1).astype(jnp.uint32)


def checksum(tree) -> jax.Array:
    """The uint32 digest of a shipped pytree (packet or whole state).
    Deterministic in content AND leaf order — the sender and receiver
    walk the same NamedTuple structure, so a match means every lane
    arrived as sent (up to the modular-sum guarantee above)."""
    total = jnp.zeros((), jnp.uint32)
    for leaf in jax.tree.leaves(tree):
        lanes = _lanes_u32(leaf)
        w = (jnp.arange(lanes.shape[0], dtype=jnp.uint32) * 2 + 1)
        total = total * jnp.uint32(_MIX) + jnp.sum(
            lanes * w, dtype=jnp.uint32
        )
    return total


def verify(tree, shipped_digest: jax.Array) -> jax.Array:
    """Receiver-side check: recompute over what arrived, compare with
    the digest that rode the wire. Returns a scalar bool (True = the
    payload is intact and may be joined)."""
    return checksum(tree) == shipped_digest


def checksum_detects(fn=checksum) -> bool:
    """The DETECTOR for checksum implementations (run by the ``faults``
    section of tools/run_static_checks.py): mint a small multi-leaf
    packet, perturb one lane at a time the way ``inject.corrupt_tree``
    does, and require the digest to change every time. The broken twin
    ``analysis.fixtures.checksum_ignores_corruption`` (a constant
    digest) fails this — proving the gate actually fires."""
    import numpy as np

    # One leaf per _lanes_u32 branch: u32/i32 pass-through, bool widen,
    # f32 bitcast, bf16 sub-4-byte bitcast+widen, and (when x64 dtypes
    # exist) a uint64 leaf whose HIGH u32 word is perturbed separately —
    # a digest that truncates 8-byte lanes to their low words must fail
    # here, not in production.
    sample = [
        jnp.arange(6, dtype=jnp.uint32).reshape(2, 3),
        jnp.array([1, 0, 3], jnp.int32),
        jnp.array([True, False], bool),
        jnp.array([1.5, -2.0], jnp.float32),
        jnp.array([0.5, 3.0], jnp.bfloat16),
    ]
    has_x64 = bool(jax.config.jax_enable_x64)
    if has_x64:
        sample.append(jnp.array([5, 9], jnp.uint64))
    sample = tuple(sample)
    base = int(np.asarray(fn(sample)))
    for i, leaf in enumerate(sample):
        flat = leaf.reshape(-1)
        bumped = (
            flat.at[0].set(~flat[0]) if leaf.dtype == bool
            else flat.at[0].add(1)
        ).reshape(leaf.shape)
        mutated = tuple(
            bumped if j == i else x for j, x in enumerate(sample)
        )
        if int(np.asarray(fn(mutated))) == base:
            return False
    if has_x64:
        u64 = sample[-1]
        hi = (
            u64.reshape(-1)
            .at[0].add(jnp.uint64(1) << jnp.uint64(32))
            .reshape(u64.shape)
        )
        mutated = tuple(
            hi if j == len(sample) - 1 else x
            for j, x in enumerate(sample)
        )
        if int(np.asarray(fn(mutated))) == base:
            return False
    return True


__all__ = ["checksum", "checksum_detects", "verify"]
