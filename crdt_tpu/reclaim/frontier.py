"""The mesh-wide stable frontier.

A dot ``(actor, c)`` is **causally stable** once every replica's top
clock covers it — from then on no replica can ever treat it as unseen,
so metadata whose only job is to decide seen-vs-unseen for dots at or
below it is dead weight (Almeida et al., "Delta State Replicated Data
Types"; Enes et al., "Efficient Synchronization of State-based CRDTs"
— both bound metadata by exactly this stability argument). The frontier
is therefore the per-actor MINIMUM over all replicas' top clocks:

    frontier[a] = min over replicas r of top_r[a]

Safety shape: the min is monotone in each input, so a straggler or a
partitioned replica simply PINS the frontier at its stale top — the
frontier stops advancing (compaction reclaims less) but never claims
stability for a dot some replica has not seen. Degradation is graceful,
never unsafe. By the same token ``frontier <= top_r`` for every
participant, which is what keeps frontier-gated compaction
read-invariant (see reclaim/compaction.py).

Three computation paths:

- :func:`stable_frontier` — pure jnp over a batched state's leading
  replica axes (host or traced; lax-only so it survives jit/shard_map).
- in-kernel, piggybacked on gossip: the ``stability=`` flag on the mesh
  entry points (parallel/anti_entropy.py) computes
  ``lax.pmin(min over local rows, replica_axis)`` on the PRE-fold input
  tops — the knowledge each replica ENTERED the round with — and
  returns it as an extra replicated output. Flag off traces nothing
  (HLO-identical program, the ``telemetry=`` discipline).
- :func:`host_frontier` — the host-side fallback for the pure-oracle
  and multihost paths: hand it every participant's top (gather across
  processes first — e.g. ``multihost._allgather_host``) and it reduces
  in numpy.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np


def top_of(state):
    """The replica's top clock ``[..., A]`` of any registered state
    pytree: the outermost ``top`` field, found by walking wrapper
    levels inward (nested kinds store ONE shared top on the innermost
    slab — the causal-composition rule pins every child top to it).
    Returns None for kinds without a clock (gset, lwwreg)."""
    seen = set()
    node = state
    while hasattr(node, "_fields") and id(node) not in seen:
        seen.add(id(node))
        if "top" in node._fields:
            return node.top
        node = node[0]  # wrapper convention: the core slab rides first
    return None


def stable_frontier(state_or_top, n_lead: Optional[int] = None):
    """Per-actor min over a batched state's replica axes: accepts a
    state pytree (top found via :func:`top_of`) or a top array
    ``[R, ..., A]`` directly. ``n_lead`` pins how many leading axes are
    replica axes (default: all but the last). Pure jnp — safe under
    jit/shard_map (the in-kernel path composes this with ``lax.pmin``
    across the mesh axis). Returns ``[A]`` (or None for clockless
    kinds)."""
    import jax.numpy as jnp

    top = state_or_top if hasattr(state_or_top, "ndim") else top_of(state_or_top)
    if top is None:
        return None
    lead = top.ndim - 1 if n_lead is None else n_lead
    return jnp.min(top, axis=tuple(range(lead))) if lead else top


def host_frontier(tops: Iterable) -> Optional[np.ndarray]:
    """Host-side frontier over an explicit collection of top clocks
    (one per replica, each ``[A]`` or a batch ``[R, A]``) — the
    fallback for the pure-oracle and multihost paths, where the
    participants are not one device batch. Multihost callers gather
    every process's local tops first (the DCN analog of the in-kernel
    pmin); a missing/stale participant's old top pins the result.
    Ragged actor widths are right-padded with 0 (an actor a participant
    never saw has min 0 — maximally conservative)."""
    mats = [np.atleast_2d(np.asarray(t)) for t in tops]
    if not mats:
        return None
    width = max(m.shape[-1] for m in mats)
    padded = [
        np.pad(m.reshape(-1, m.shape[-1]), ((0, 0), (0, width - m.shape[-1])))
        for m in mats
    ]
    return np.concatenate(padded, axis=0).min(axis=0)


def model_frontier(model) -> Optional[np.ndarray]:
    """The frontier of one batched model's OWN replica rows — the
    self-contained form checkpoint compact-on-save and
    :func:`..reclaim.compact_model` use when the device batch IS the
    replica set. For a model that is one shard of a larger mesh, use
    :func:`host_frontier` over every shard's tops instead (a local min
    over a subset may claim stability for dots remote replicas lack)."""
    top = top_of(model.state)
    if top is None:
        return None
    return np.asarray(top).reshape(-1, top.shape[-1]).min(axis=0)


def frontier_lag(top, frontier):
    """How far knowledge has run ahead of stability: the max over
    replicas and actor lanes of ``top - frontier`` (0 = fully stable
    mesh). The in-jit gauge behind the ``frontier_lag`` telemetry
    field; a growing lag under steady traffic means some replica is
    pinning the frontier (straggler/partition) and reclamation is
    stalled — the operator signal VERDICT r5 asks for. Pure jnp; lanes
    BEHIND the frontier (an identity-padded row, a restored straggler)
    clamp to 0 rather than wrapping the unsigned difference."""
    import jax.numpy as jnp

    t = jnp.asarray(top)
    f = jnp.asarray(frontier).astype(t.dtype)
    return jnp.max(jnp.maximum(t, f) - f).astype(jnp.uint32)


# Kinds whose frontier-stall warning already fired this process —
# repeats only count in the registry (the _warn_residue dedupe pattern,
# parallel/delta_ring.py).
_STALL_WARNED: set = set()


def reset_stall_warnings() -> None:
    """Re-arm the once-per-kind frontier-stall warning (tests; or after
    an operator evicted the straggler and wants fresh signal)."""
    _STALL_WARNED.clear()


def watch_lag(kind: str, lag: int, lag_threshold) -> None:
    """The alert the docstring above promises: ``frontier_lag`` is "the
    stall signal", and this is what watches it. Called host-side by the
    gossip entry points when ``lag_threshold=`` is set (needs
    ``stability=``): a lag at or past the threshold counts
    ``reclaim.frontier_stalled`` on EVERY occurrence — the rate an
    operator can alert on — and warns once per kind per process (the
    ``_warn_residue`` dedupe discipline: a stalled mesh in a gossip
    loop must not emit one warning per round). A sustained stall means
    some replica is pinning the frontier — investigate the straggler,
    or evict it (crdt_tpu/faults/membership.py) to unpin."""
    from ..utils.metrics import metrics

    if lag_threshold is None or lag < lag_threshold:
        return
    metrics.count("reclaim.frontier_stalled")
    if kind in _STALL_WARNED:
        return
    _STALL_WARNED.add(kind)
    import warnings

    warnings.warn(
        f"{kind}: frontier_lag={lag} >= lag_threshold={lag_threshold} — "
        f"a straggler is pinning the stable frontier and reclamation is "
        f"stalled; investigate or evict the rank "
        f"(crdt_tpu.faults.Membership). Warned once per kind; repeats "
        f"count in reclaim.frontier_stalled",
        stacklevel=3,
    )


__all__ = [
    "frontier_lag", "host_frontier", "model_frontier",
    "reset_stall_warnings", "stable_frontier", "top_of", "watch_lag",
]
