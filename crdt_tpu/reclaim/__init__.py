"""Causal-stability reclamation — the inverse of the growth story.

PR 1 gave every bounded structure an overflow→widen→resume loop, so a
long-lived replica under churn only ever GROWS: capacity ratchets up at
the occupancy peak and nothing ever computes a clock that is *safe* to
forget mesh-wide (``traits.ResetRemove`` exists, but the caller supplies
the clock). This package closes the loop with three layers:

- :mod:`.frontier` — the mesh-wide **stable frontier**: the per-actor
  minimum over every replica's top clock. Every dot at or below it has
  been seen by every replica (delta-state causal stability, Almeida et
  al. 1603.01529; Enes et al. 1803.02750), so state it dominates can be
  discarded without any replica ever noticing. Computed as a lax-only
  ``pmin`` piggybacked on gossip rounds (``stability=`` on the mesh
  entry points, default off and HLO-identical off — the ``telemetry=``
  discipline), with a host-side fallback for the pure/multihost paths.
  A straggler or partitioned replica simply pins the frontier:
  degradation is graceful, never unsafe.
- :mod:`.compaction` — per-kind frontier-driven compaction: retire
  parked-remove slots the frontier has caught up to, scrub stale dead
  payload, repack. Observable reads are bit-identical before/after
  (the compaction-invariance law in ``analysis/laws.py`` pins
  ``canonical(read(compact(s))) == canonical(read(s))`` and
  merge/compact commutation for every registered kind).
- ``elastic.shrink`` / ``elastic.Hysteresis`` — the inverse of
  ``elastic.widen``: ops-level ``narrow``/``narrow_span`` kernels
  (refused when occupancy does not fit) under a hysteresis policy
  (shrink only after occupancy sits below the low-water mark for K
  consecutive rounds, never below a floor) so widen/shrink cannot
  thrash. Re-exported here so one import serves the subsystem.

Host-side actor-lane compaction for the counter family lives in
:mod:`crdt_tpu.lifecycle` (``compact_actors``) and feeds the same
``reclaim.*`` counters.
"""

from .compaction import (
    compact_model,
    compact_state,
    record_reclaim,
)
from .frontier import (
    frontier_lag,
    host_frontier,
    model_frontier,
    reset_stall_warnings,
    stable_frontier,
    top_of,
    watch_lag,
)

# The shrink half lives in elastic.py (it IS the inverse of widen and
# shares the axis tables); re-exported lazily for one-stop imports —
# a module-level import here would cycle (elastic -> models -> ops ->
# reclaim.compaction triggers this package __init__).
def __getattr__(name):
    if name in ("Hysteresis", "shrink"):
        from .. import elastic

        return getattr(elastic, name)
    raise AttributeError(name)


__all__ = [
    "Hysteresis", "compact_model", "compact_state", "frontier_lag",
    "host_frontier", "model_frontier", "record_reclaim",
    "reset_stall_warnings", "shrink", "stable_frontier", "top_of",
    "watch_lag",
]
