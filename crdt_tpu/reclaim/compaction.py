"""Frontier-driven space reclamation — shared kernels + the model driver.

What compaction may discard is bounded by one invariant: **observable
reads are bit-identical before and after** (the compaction-invariance
law in ``analysis/laws.py`` pins it for every registered kind). Under
that bound, the sound reclamation for the masked-epoch buffers is:

- **retire stable parked removes** — a parked slot whose rm clock the
  frontier dominates has been replayed by every replica (each top >=
  frontier >= the slot clock), so it can never kill another dot
  anywhere; dropping it is the eager form of what the next join's
  caught-up check does. Gated on BOTH the frontier and the local top
  (``frontier <= top`` holds for every frontier participant, but a
  restored straggler may trail the mesh — the extra bound keeps the
  kernel read-invariant unconditionally rather than relying on the
  caller's frontier discipline);
- **scrub stale dead payload** — the CmRDT appliers drop a caught-up
  slot's ``dvalid`` without zeroing its clock/mask lanes (see
  analysis/canon.py), and dense ``apply_add`` leaves dead-slot payload
  behind; compaction zeroes it and repacks valid slots to the front, so
  the state is byte-comparable and the freed tail is genuine headroom
  for ``elastic.shrink``.

Per-kind ``compact(state, frontier)`` kernels live at the bottom of
each ``ops/*.py`` (composed from :func:`retire_epochs` here) and
register via ``analysis.registry.register_compactor`` — an unregistered
kind fails tests/test_analysis.py discovery, the same contract as joins
and mesh entry points. Kernels are pure lax/jnp on static shapes, so
the ``stability=`` gossip flag can run them in-kernel on the converged
rows, and return ``(state, freed_slots, freed_bytes)`` scalars feeding
the ``reclaimed_slots`` / ``reclaimed_bytes`` telemetry fields.
"""

from __future__ import annotations

from .frontier import model_frontier


def retire_epochs(dcl, payload, dvalid, top, frontier, payload_fill=0):
    """Retire + scrub one masked-epoch buffer level.

    ``dcl [..., D, A]`` parked rm clocks, ``payload [..., D, X]``
    member masks / key masks / id lists (``payload_fill`` is the dead
    value — 0/False for masks, -1 for id lists), ``dvalid [..., D]``,
    ``top [..., A]`` the state's top clock, ``frontier [A]`` (or None
    to skip retirement and only scrub).

    Returns ``(dcl, payload, dvalid, freed_slots, freed_bytes)`` with
    valid slots repacked to the front (stable, matching the joins'
    ``_compact`` convention) and dead lanes zeroed/filled.
    ``freed_slots`` (uint32) counts retired slots plus scrubbed stale
    dead lanes; ``freed_bytes`` (float32) counts only the retired
    slots' static lane bytes — scrubbed lanes were already dead.

    Staleness is detected on the CLOCK plane only (a dead slot whose
    clock is nonzero): the clock plane is replicated across element
    shards on every kind, so the count stays shard-consistent inside
    ``shard_map`` even where the payload plane (dense member/key masks)
    is element-sharded. Payload-only stale lanes are still SCRUBBED —
    they just are not counted — and the only writer that zeroes a dead
    slot's clock while leaving payload (``reset_remove``) scrubs its
    own payload, so the undercount is nil in practice."""
    import jax.numpy as jnp

    stale = ~dvalid & jnp.any(dcl != 0, axis=-1)
    if frontier is None:
        covered = jnp.zeros_like(dvalid)
    else:
        frontier = jnp.asarray(frontier, dcl.dtype)
        covered = (
            dvalid
            & jnp.all(dcl <= frontier, axis=-1)
            & jnp.all(dcl <= top[..., None, :], axis=-1)
        )
    dvalid = dvalid & ~covered

    # Scrub + repack (valid-first, stable — the `_compact_*` order).
    order = jnp.argsort(~dvalid, axis=-1, stable=True)
    dcl = jnp.take_along_axis(dcl, order[..., None], axis=-2)
    payload = jnp.take_along_axis(payload, order[..., None], axis=-2)
    dvalid = jnp.take_along_axis(dvalid, order, axis=-1)
    dcl = jnp.where(dvalid[..., None], dcl, jnp.zeros_like(dcl))
    payload = jnp.where(
        dvalid[..., None], payload, jnp.full_like(payload, payload_fill)
    )

    slot_bytes = (
        dcl.shape[-1] * dcl.dtype.itemsize
        + payload.shape[-1] * payload.dtype.itemsize
        + dvalid.dtype.itemsize
    )
    freed_slots = jnp.sum(covered, dtype=jnp.uint32) + jnp.sum(
        stale, dtype=jnp.uint32
    )
    freed_bytes = jnp.sum(covered, dtype=jnp.float32) * slot_bytes
    return dcl, payload, dvalid, freed_slots, freed_bytes


def compact_state(state, frontier, kind: str):
    """Run ``kind``'s registered compactor on ``state``. Returns
    ``(state, freed_slots, freed_bytes)`` (freed as device scalars)."""
    from ..analysis.registry import get_compactor

    return get_compactor(kind).compact(state, frontier)


def record_reclaim(kind: str, slots: int, nbytes: float) -> None:
    """Feed the host registry: ``reclaim.reclaimed_slots`` /
    ``reclaim.reclaimed_bytes`` (plus the per-kind variants) — the same
    names the in-kernel Telemetry fields drain under, so host-side
    paths (checkpoint compact-on-save, ``lifecycle.compact_actors``)
    and the in-kernel path share one counter namespace."""
    from ..utils.metrics import metrics

    metrics.count("reclaim.reclaimed_slots", int(slots))
    metrics.count(f"reclaim.reclaimed_slots.{kind}", int(slots))
    metrics.count("reclaim.reclaimed_bytes", int(nbytes))


def compact_model(model, frontier=None) -> dict:
    """Compact a batched model IN PLACE against ``frontier`` (default:
    the model's own replica rows' frontier — sound when the device
    batch is the whole replica set; pass a mesh-wide
    ``host_frontier(...)`` when it is one shard of a larger mesh).
    Returns ``{"reclaimed_slots": int, "reclaimed_bytes": int}`` and
    feeds the ``reclaim.*`` counters. Covers the elastic model family
    (elastic.kind_of)."""
    from .. import elastic
    from ..telemetry import span

    kind = elastic.kind_of(model)
    if frontier is None:
        frontier = model_frontier(model)
    with span("reclaim.compact", kind=kind):
        state, slots, nbytes = compact_state(model.state, frontier, kind)
    model.state = state
    slots, nbytes = int(slots), int(nbytes)
    record_reclaim(kind, slots, nbytes)
    return {"reclaimed_slots": slots, "reclaimed_bytes": nbytes}


__all__ = [
    "compact_model", "compact_state", "record_reclaim", "retire_epochs",
]


def _noop_compact(state, frontier):
    """The identity compactor for kinds with nothing reclaimable
    (gset/lwwreg/vclock: no parked buffers, no dead payload lanes).
    Registered so the coverage contract stays total."""
    import jax.numpy as jnp

    return state, jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.float32)
