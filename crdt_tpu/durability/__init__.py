"""crdt_tpu.durability — crash-consistent durability.

PR 8 made the *mesh* survive lost packets, corruption, and dead ranks;
this package makes the *host process* survive. Four cooperating pieces
(see each module's docstring):

- :mod:`.wal` — a host-side append-only **write-ahead δ-log**: records
  are join-irreducible decomposition lanes (``delta_opt.decompose``,
  minted over the last logged state), framed with length + CRC so a
  torn tail is detected and truncated on open, with segment rotation
  and an ``every_n`` / ``on_round`` fsync policy. Accepted via ``wal=``
  on ``run_delta_ring`` + the four ``mesh_delta_gossip*`` flavors,
  ``delta_gossip_elastic``, and ``mesh_stream_fold*`` (which also
  persists ``StreamInterrupted`` resume state).
- :mod:`.snapshot` — **generational atomic snapshots** layered on
  ``checkpoint.py``: per-array content checksums in a manifest,
  fsync-before-rename, manifest-commit-last, retain-K generations,
  compact-on-save; snapshot + WAL-suffix replay reconstructs state
  bit-identically.
- :mod:`.recover` — the **recovery driver**: newest VALID generation
  (corrupt manifests/arrays fall back a generation with a longer
  replay), WAL suffix replayed through one memoised jitted scan-fold
  (the ``delta_opt/heal.py`` pattern), plus the **log-suffix rejoin**
  that upgrades PR 8's membership contract: a restarted rank recovers
  locally and ships snapshot-generation + log-suffix divergence lanes
  instead of receiving full state (``bench.py --recovery`` measures
  the byte win).
- :mod:`.crashpoints` — **deterministic crash-point injection**: every
  durability I/O boundary registers a named crashpoint; the fuzz loop
  kills at each one, recovers, and asserts bit-identity with the
  uninterrupted run (registration is the coverage contract).

Plus :func:`static_checks` — the ``durability`` section of
tools/run_static_checks.py: crashpoint coverage, the kill-then-recover
contract over every crashpoint, and the broken-twin detector gates
(the no-fsync WAL and the checksum-ignoring loader in
``analysis.fixtures`` must each be caught).
"""

from __future__ import annotations

from typing import List

from . import crashpoints
from .crashpoints import SimulatedCrash
from .recover import (
    RecoveryReport,
    RejoinReport,
    load_stream_resume,
    recover_model,
    recover_state,
    rejoin,
    replay,
)
from .snapshot import SnapshotCorrupt, loader_detects_corruption
from .wal import Wal, WalCorrupt, fsync_honored

from . import recover, snapshot, wal  # noqa: F401  (module re-exports)


def _probe_states(n: int = 6):
    """Tiny host pytrees for the static-check workload (full-state
    records: no registered kind or kernel compile needed — the δ-replay
    fuzz over real decompositions lives in tests/test_durability.py)."""
    import numpy as np

    return [
        {
            "top": np.arange(8, dtype=np.uint32) + i,
            "ctr": (np.arange(24, dtype=np.uint32).reshape(8, 3) * (i + 1)),
        }
        for i in range(n)
    ]


def _probe_workload(root: str, states) -> None:
    """The canonical micro-workload — crosses EVERY registered
    crashpoint when run uninterrupted: tiny segments force WAL
    rotation, retain=1 with repeated saves forces pruning, one
    serving-tier tenant persist/restore crosses the ``serve.evict.*``
    / ``serve.restore.*`` boundaries (crdt_tpu/serve/evict.py — the
    evict write-ordering the fuzz loop must be able to kill inside),
    one fan-out subscribe→push→ack round crosses the
    ``fanout.ack.*`` boundaries (crdt_tpu/fanout/plane.py — promote
    and resync, the subscription state the fuzz loop kills inside),
    and one WAL-logged pipelined flush + background persist drain
    crosses the ``serve.wal.*`` / ``serve.dispatch.*`` /
    ``serve.persist.*`` boundaries (crdt_tpu/serve/wal.py + loop.py).
    The serve and fanout tails never touch the main wal/snap dirs, so
    ``_probe_recover``'s last-durable-record contract is unchanged."""
    import os

    import jax
    import numpy as np

    w = Wal(
        os.path.join(root, "wal"), fsync="every_n", every_n=1,
        segment_bytes=256,
    )
    sdir = os.path.join(root, "snap")
    for i, s in enumerate(states[1:], 1):
        # jax.tree leaf order (the replay unflatten convention).
        w.append(
            {"rtype": "state", "kind": "probe"}, jax.tree.leaves(s),
        )
        if i % 2 == 0:
            snapshot.save_state(
                sdir, "probe", s, wal_seq=w.last_seq, retain=1,
            )
    w.close()
    from ..serve.evict import persist_tenant, restore_tenant

    persist_tenant(os.path.join(root, "serve"), "probe", 0, states[-1])
    restore_tenant(os.path.join(root, "serve"), "probe", 0, states[0])
    # The fan-out tail: window_cap=0 degrades the one dirty ⊥-watermark
    # subscriber straight to resync (fanout.ack.pre_resync — no wire
    # dispatch to compile), then the genuine ack promotes its watermark
    # (fanout.ack.pre_promote / post_promote). Host-side registry state
    # only — nothing durable, the recovery contract is untouched.
    from ..fanout import FanoutPlane
    from ..parallel import make_mesh
    from ..serve import Superblock

    sb = Superblock(
        1, make_mesh(1, 1), kind="orswot",
        caps=dict(n_elems=4, n_actors=2, deferred_cap=2),
    )
    plane = FanoutPlane(sb, window_cap=0, dispatch_lanes=1)
    ids = plane.subscribe([0])
    plane.note_dirty([0])
    plane.push()
    plane.ack(ids)
    # The pipelined-serving tail (ISSUE 18): one WAL-logged flush
    # crosses serve.wal.pre_log / serve.wal.post_log_pre_dispatch /
    # serve.dispatch.post_scatter_pre_ack, then one background persist
    # drain crosses serve.persist.background_drain. Writes only under
    # root/serve* (its own ServeWal dir + evictor tier), so
    # ``_probe_recover``'s last-durable-record contract over root/wal +
    # root/snap is untouched.
    from ..serve import (
        BackgroundPersister, Evictor, IngestQueue, ServeWal,
    )

    swal = ServeWal(os.path.join(root, "serve_wal"))
    try:
        ev = Evictor(sb, os.path.join(root, "serve_evict"))
        q = IngestQueue(sb, lanes=1, depth=2, evictor=ev, wal=swal)
        q.add(0, 0, 1, np.isin(np.arange(4), [0]))
        q.drain()
        bp = BackgroundPersister(ev, batch=1)
        bp.enqueue([0])
        bp.drain()
    finally:
        swal.close()


def _probe_recover(root: str, states):
    """Recovery for the probe workload: reopen the WAL (torn-tail
    truncation happens here), recover snapshot + suffix, and return
    the pair ``(recovered, expected)`` — expected is the state of the
    last DURABLE record (seq indexes the states list by construction).
    """
    import os

    w = Wal(os.path.join(root, "wal"))
    try:
        got, _ = recover_state(
            os.path.join(root, "snap"), w, states[0], kind="probe",
            default=states[0],
        )
        return got, states[w.last_seq]
    finally:
        w.close()


def static_checks() -> List:
    """The ``durability`` static-check section (Finding list, empty =
    clean):

    1. **crashpoint coverage** — every registered crashpoint must be
       crossed by the canonical micro-workload (a dead crashpoint is an
       I/O boundary the fuzz loop silently stopped exercising);
    2. **recovery contract** — for EVERY crashpoint, kill-then-recover
       on the probe workload lands exactly the last durable record,
       bit-identically (the full per-kind δ-decomposition matrix runs
       in tests/test_durability.py across tiers);
    3. **fsync policy** — ``wal.fsync_honored`` must pass the honest
       :class:`Wal` and FAIL the no-fsync broken twin
       (``analysis.fixtures.wal_skips_fsync``);
    4. **loader integrity** — ``snapshot.loader_detects_corruption``
       must pass the honest ``load_newest`` and FAIL the
       checksum-ignoring twin
       (``analysis.fixtures.snapshot_load_unchecked``).
    """
    import shutil
    import tempfile

    import numpy as np

    from ..analysis import fixtures
    from ..analysis.report import Finding

    findings: List[Finding] = []
    states = _probe_states()

    def equal(a, b):
        import jax

        xa = [np.asarray(x) for x in jax.tree.leaves(a)]
        xb = [np.asarray(x) for x in jax.tree.leaves(b)]
        return len(xa) == len(xb) and all(
            x.shape == y.shape and bool((x == y).all())
            for x, y in zip(xa, xb)
        )

    # 1. coverage
    tmp = tempfile.mkdtemp(prefix="durability-gate-")
    try:
        with crashpoints.recording() as crossed:
            _probe_workload(tmp, states)
        missing = sorted(set(crashpoints.registered()) - crossed)
        for name in missing:
            findings.append(Finding(
                "crashpoint-coverage", name,
                "registered crashpoint never crossed by the canonical "
                "workload — the fuzz loop cannot exercise this I/O "
                "boundary",
            ))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # 2. kill-then-recover at every crashpoint — routed through the
    # one fuzz engine (crashpoints.fuzz), same as the test matrix.
    box: dict = {}
    dirs: List[str] = []

    def crash_run(name):
        box["dir"] = tempfile.mkdtemp(prefix="durability-fuzz-")
        dirs.append(box["dir"])
        _probe_workload(box["dir"], states)

    def recov():
        return _probe_recover(box["dir"], states)

    try:
        for failure in crashpoints.fuzz(crash_run, recov, equal):
            findings.append(Finding(
                "recovery-contract", failure.split(":", 1)[0], failure,
            ))
    finally:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)

    # 3. fsync policy + broken twin
    tmp = tempfile.mkdtemp(prefix="durability-fsync-")
    try:
        if not fsync_honored(Wal, tmp):
            findings.append(Finding(
                "fsync-policy", "wal.Wal",
                "the honest WAL issued fewer fsync barriers than its "
                "every_n=1 policy promises — appends are not durable "
                "across power loss",
            ))
        if fsync_honored(fixtures.wal_skips_fsync, tmp):
            findings.append(Finding(
                "broken-fixture-missed", "wal_skips_fsync",
                "the no-fsync WAL twin PASSED the fsync detector — the "
                "durability gate is not actually firing",
            ))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # 4. loader integrity + broken twin
    if not loader_detects_corruption(
        lambda d, t: snapshot.load_newest(d, t)
    ):
        findings.append(Finding(
            "loader-integrity", "snapshot.load_newest",
            "a flipped payload byte loaded without complaint — rotten "
            "state would reach a resuming mesh",
        ))
    if loader_detects_corruption(fixtures.snapshot_load_unchecked):
        findings.append(Finding(
            "broken-fixture-missed", "snapshot_load_unchecked",
            "the checksum-ignoring loader twin PASSED the corruption "
            "detector — the integrity gate is not actually firing",
        ))
    return findings


__all__ = [
    "RecoveryReport", "RejoinReport", "SimulatedCrash", "SnapshotCorrupt",
    "Wal", "WalCorrupt", "crashpoints", "fsync_honored",
    "load_stream_resume", "loader_detects_corruption", "recover",
    "recover_model", "recover_state", "rejoin", "replay", "snapshot",
    "static_checks", "wal",
]
