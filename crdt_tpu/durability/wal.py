"""Host-side append-only write-ahead δ-log.

The δ-buffer discipline of Almeida et al. ("Delta State Replicated
Data Types", arXiv 1603.01529) stores the inflation, not the state;
PR 9's join-irreducible decomposition (``delta_opt.decompose``, Enes
et al. 1803.02750) gives the minimal on-disk unit: a WAL record is the
irredundant lane set of one state transition over the previously
logged state (positional diff — exact regardless of lattice order, so
replay reproduces every logged state bit-identically), not a full
state. Snapshot + WAL-suffix replay is then the whole recovery story
(``durability.recover``).

On-disk format (little-endian), built for torn-tail detection:

- a **segment** file (``wal-<n>.seg``) opens with the 8-byte magic
  ``CRDTWAL1`` and carries a run of frames;
- a **frame** is ``[magic u32][seq u64][length u64][crc32 u32]`` +
  ``length`` payload bytes; ``seq`` increases by exactly 1 across the
  whole log (segments included), ``crc32`` covers the payload;
- the **payload** is one ``.npz`` image: a ``meta`` JSON blob
  (``rtype`` ∈ {``delta``, ``state``, ``resume``}, the merge ``kind``,
  batching) plus the numbered leaves of the record pytree.

``open`` scans every segment in order and TRUNCATES at the first
damage — a short frame header, a short payload, a CRC mismatch, a seq
gap — counting ``durability.torn_tail_truncated``; frames after the
damage (including whole later segments) are unreachable by contract: a
WAL replay must be a contiguous prefix, and re-appending after the
truncation point overwrites the garbage.

Fsync policy (the durability/latency trade): ``fsync="every_n"``
(default, ``every_n=1``) fsyncs the segment after every n-th append —
crash loses at most n-1 records; ``fsync="on_round"`` fsyncs only at
:meth:`Wal.mark_round` — the mesh-round batching mode (one barrier per
gossip round however many records it minted; crash loses at most one
round). Records are FLUSHED to the OS either way; fsync is the
power-loss barrier, and :func:`fsync_honored` statically proves the
policy's calls actually happen (the no-fsync broken twin in
``analysis.fixtures`` proves the prover).

Crashpoints (``durability.crashpoints``) bracket every I/O boundary;
the fuzz loop kills at each and recovery must land bit-identical.
"""

from __future__ import annotations

import io
import json
import os
import re
import struct
import zlib
from typing import Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..obs import trace as obs_trace
from ..utils.metrics import metrics
from . import crashpoints as cp

SEGMENT_MAGIC = b"CRDTWAL1"
FRAME = struct.Struct("<IQQI")  # magic, seq, payload length, crc32
FRAME_MAGIC = 0x57A1F00D
_SEG_RE = re.compile(r"^wal-(\d{8})\.seg$")

CP_PRE_APPEND = cp.register(
    "wal.pre_append", "before any byte of the new frame is written"
)
CP_MID_APPEND = cp.register(
    "wal.mid_append",
    "frame header flushed, payload not yet written — the torn tail",
)
CP_POST_APPEND_PRE_FSYNC = cp.register(
    "wal.post_append_pre_fsync",
    "frame fully flushed to the OS, fsync barrier not yet issued",
)
CP_POST_FSYNC = cp.register(
    "wal.post_fsync", "append fsynced — the record is durable"
)
CP_PRE_ROTATE = cp.register(
    "wal.pre_rotate", "segment full; before the new segment exists"
)
CP_POST_ROTATE = cp.register(
    "wal.post_rotate_pre_fsync_dir",
    "new segment created and fsynced, directory entry not yet fsynced",
)


class WalCorrupt(RuntimeError):
    """Damage the open-scan could not repair by truncation (unreadable
    directory, a segment that vanished mid-scan)."""


def _payload(meta: dict, leaves) -> bytes:
    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(
            json.dumps(meta, sort_keys=True).encode("utf-8"), dtype=np.uint8
        ),
        **{f"a_{i}": np.asarray(x) for i, x in enumerate(leaves)},
    )
    return buf.getvalue()


def _parse_payload(raw: bytes) -> Tuple[dict, list]:
    with np.load(io.BytesIO(raw)) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        n = sum(1 for k in z.files if k.startswith("a_"))
        leaves = [z[f"a_{i}"] for i in range(n)]
    return meta, leaves


class Wal:
    """One rank's append-only write-ahead δ-log (module docstring).

    ``segment_bytes`` bounds a segment's size (rotation is checked
    before each append, so one oversized record still lands whole).
    ``tail`` is the last logged state — the ``since`` every
    :meth:`append_state` decomposes over; :meth:`attach` seeds it with
    a DEVICE COPY so zero-copy (donating) mesh entries can consume
    their input buffers without invalidating the log's diff base."""

    def __init__(
        self,
        path,
        *,
        fsync: str = "every_n",
        every_n: int = 1,
        segment_bytes: int = 64 * 1024 * 1024,
    ):
        if fsync not in ("every_n", "on_round"):
            raise ValueError(
                f"fsync policy {fsync!r} not in ('every_n', 'on_round')"
            )
        if every_n < 1:
            raise ValueError("every_n must be >= 1")
        self.path = os.fspath(path)
        self.fsync_policy = fsync
        self.every_n = every_n
        self.segment_bytes = segment_bytes
        self.fsyncs = 0            # fsync barriers issued (telemetry)
        self.bytes_appended = 0    # payload+frame bytes appended
        self.torn_tails = 0        # truncations performed by open-scan
        self._tail = None          # last logged state (device copy)
        self._pending = 0          # appends since the last fsync
        self._f = None
        os.makedirs(self.path, exist_ok=True)
        self._scan_and_open()

    # ---- open / recovery scan -------------------------------------------

    def _segments(self):
        try:
            names = os.listdir(self.path)
        except OSError as exc:
            raise WalCorrupt(f"cannot list WAL dir {self.path!r}: {exc}")
        segs = sorted(
            (int(m.group(1)), n)
            for n in names
            if (m := _SEG_RE.match(n))
        )
        return [(i, os.path.join(self.path, n)) for i, n in segs]

    def _truncate(self, seg_path: str, pos: int, why: str) -> None:
        with open(seg_path, "r+b") as f:
            f.truncate(pos)
            f.flush()
            os.fsync(f.fileno())
        self.torn_tails += 1
        metrics.count("durability.torn_tail_truncated")
        metrics.count(f"durability.torn_tail.{why}")
        obs.emit("wal_torn_tail", why=why, at=pos)

    def _scan_and_open(self) -> None:
        """Validate every segment, truncate at the first damage, drop
        unreachable later segments, and open the last segment for
        append (creating segment 1 on an empty dir)."""
        self.last_seq = 0
        segs = self._segments()
        damaged = False
        keep = []
        for idx, (seg_no, seg_path) in enumerate(segs):
            if damaged:
                # Frames past a truncation are unreachable by the
                # contiguous-prefix contract; drop the whole segment.
                os.unlink(seg_path)
                continue
            with open(seg_path, "rb") as f:
                head = f.read(len(SEGMENT_MAGIC))
                if head != SEGMENT_MAGIC:
                    self._truncate(seg_path, 0, "bad_segment_header")
                    damaged = True
                    if idx == 0 or head:
                        keep.append((seg_no, seg_path))
                    else:
                        os.unlink(seg_path)
                    continue
                pos = len(SEGMENT_MAGIC)
                while True:
                    hdr = f.read(FRAME.size)
                    if not hdr:
                        break  # clean end of segment
                    if len(hdr) < FRAME.size:
                        self._truncate(seg_path, pos, "short_frame")
                        damaged = True
                        break
                    magic, seq, length, crc = FRAME.unpack(hdr)
                    if magic != FRAME_MAGIC or seq != self.last_seq + 1:
                        why = ("bad_frame_magic" if magic != FRAME_MAGIC
                               else "seq_gap")
                        self._truncate(seg_path, pos, why)
                        damaged = True
                        break
                    payload = f.read(length)
                    if len(payload) < length:
                        self._truncate(seg_path, pos, "short_payload")
                        damaged = True
                        break
                    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                        self._truncate(seg_path, pos, "crc_mismatch")
                        damaged = True
                        break
                    self.last_seq = seq
                    pos = f.tell()
            keep.append((seg_no, seg_path))
        if not keep:
            self._new_segment(1)
        else:
            self._seg_no, seg_path = keep[-1]
            self._size = os.path.getsize(seg_path)
            self._f = open(seg_path, "ab")
            if self._size < len(SEGMENT_MAGIC):
                # A truncated-to-zero segment (bad header) re-arms as
                # the append target: rewrite the header so future scans
                # accept what lands after it.
                self._f.write(SEGMENT_MAGIC)
                self._f.flush()
                self._fsync(self._f)
                self._size = len(SEGMENT_MAGIC)

    def _new_segment(self, seg_no: int) -> None:
        cp.hit(CP_PRE_ROTATE)
        seg_path = os.path.join(self.path, f"wal-{seg_no:08d}.seg")
        f = open(seg_path, "wb")
        f.write(SEGMENT_MAGIC)
        f.flush()
        self._fsync(f)
        cp.hit(CP_POST_ROTATE)
        from ..checkpoint import fsync_dir

        fsync_dir(self.path)
        self._seg_no = seg_no
        self._size = len(SEGMENT_MAGIC)
        self._f = f

    # ---- append ----------------------------------------------------------

    def _fsync(self, f) -> None:
        """The power-loss barrier — one overridable seam so the
        fsync-policy detector (and its broken twin) can prove the calls
        happen (module docstring). Each barrier advances the DURABLE
        watermark: records up to ``last_seq`` now survive power loss —
        the ``durability.wal.watermark`` gauge and the ``wal_fsync``
        flight event both carry it (exporter.health reads the gauge;
        tools/obs_report.py lines the events up against losses)."""
        os.fsync(f.fileno())
        self.fsyncs += 1
        metrics.count("durability.fsyncs")
        metrics.observe("durability.wal.watermark", float(self.last_seq))
        # Group commit IS the durable point for every dispatched op in
        # the round — stamp all dispatched-not-yet-durable traces at
        # once (no tenant scope: the barrier covers the whole batch).
        obs_trace.stamp("durable")
        obs.emit("wal_fsync", watermark=self.last_seq,
                 bytes=self.bytes_appended)

    def append(self, meta: dict, leaves) -> int:
        """Append one record (``meta`` + pytree leaves); returns its
        seq. Low-level — prefer :meth:`append_state` /
        :meth:`append_resume`."""
        if self._f is None:
            raise WalCorrupt("WAL is closed")
        cp.hit(CP_PRE_APPEND)
        if self._size >= self.segment_bytes + len(SEGMENT_MAGIC):
            old = self._f
            old.flush()
            self._fsync(old)
            old.close()
            self._new_segment(self._seg_no + 1)
        payload = _payload(meta, leaves)
        seq = self.last_seq + 1
        hdr = FRAME.pack(
            FRAME_MAGIC, seq, len(payload),
            zlib.crc32(payload) & 0xFFFFFFFF,
        )
        self._f.write(hdr)
        self._f.flush()  # the torn frame is really on disk (crash model)
        cp.hit(CP_MID_APPEND)
        self._f.write(payload)
        self._f.flush()
        cp.hit(CP_POST_APPEND_PRE_FSYNC)
        self.last_seq = seq
        self._pending += 1
        n = len(hdr) + len(payload)
        self._size += n
        self.bytes_appended += n
        metrics.count("durability.wal_bytes", n)
        metrics.count("durability.wal_records")
        if self.fsync_policy == "every_n" and self._pending >= self.every_n:
            self._fsync(self._f)
            self._pending = 0
            cp.hit(CP_POST_FSYNC)
        return seq

    def mark_round(self) -> None:
        """A mesh-round boundary: under ``fsync='on_round'`` this is
        THE barrier (one fsync per round, however many records the
        round minted); a no-op when nothing is pending."""
        if self._pending and self.fsync_policy == "on_round":
            self._fsync(self._f)
            self._pending = 0
            cp.hit(CP_POST_FSYNC)

    # ---- δ records over the attached tail --------------------------------

    @property
    def tail(self):
        return self._tail

    def attach(self, state) -> None:
        """Seed the diff base with a DEVICE COPY of ``state`` (safe to
        call before a donating mesh entry consumes the original)."""
        self._tail = jax.tree.map(jnp.copy, state)

    def _same_shape(self, state) -> bool:
        a = jax.tree.leaves(self._tail)
        b = jax.tree.leaves(state)
        return (
            jax.tree.structure(self._tail) == jax.tree.structure(state)
            and len(a) == len(b)
            and all(
                x.shape == y.shape and x.dtype == y.dtype
                for x, y in zip(a, b)
            )
        )

    def append_state(self, kind: str, state, *, batched: bool = True) -> int:
        """Log one state transition as an irreducible δ record:
        ``decompose(state, tail)`` for registered merge ``kind``
        (``batched=True`` vmaps over the leading replica axis — the
        mesh ``[P, ...]`` convention). A shape/structure change since
        the tail (an elastic widen) falls back to a full-``state``
        record (``durability.wal_full_state_records``) — positional
        diffs require congruent layouts. Updates the tail."""
        if self._tail is None:
            raise ValueError(
                "no diff base: call attach(state) with the pre-run state "
                "before the first append_state"
            )
        if not self._same_shape(state):
            metrics.count("durability.wal_full_state_records")
            seq = self.append(
                {"rtype": "state", "kind": kind, "batched": batched},
                [np.asarray(x) for x in jax.tree.leaves(state)],
            )
        else:
            from ..delta_opt.decompose import decompose

            if batched:
                d = jax.vmap(lambda s, o: decompose(kind, s, o))(
                    state, self._tail
                )
            else:
                d = decompose(kind, state, self._tail)
            seq = self.append(
                {"rtype": "delta", "kind": kind, "batched": batched},
                [np.asarray(x) for x in jax.tree.leaves(d)],
            )
        self._tail = jax.tree.map(jnp.copy, state)
        return seq

    def append_resume(self, kind: str, acc, blocks_done: int) -> int:
        """Persist a replica-stream resume point (``parallel.stream``):
        the accumulator — by construction the exact join of blocks
        ``[0, blocks_done)`` — plus the index to resume from. The
        newest resume record wins (``recover.load_stream_resume``)."""
        metrics.count("durability.stream_resume_records")
        return self.append(
            {"rtype": "resume", "kind": kind, "blocks_done": int(blocks_done)},
            [np.asarray(x) for x in jax.tree.leaves(acc)],
        )

    # ---- read ------------------------------------------------------------

    def records(self, since_seq: int = 0) -> Iterator[Tuple[int, dict, list]]:
        """Yield ``(seq, meta, leaves)`` for every valid record with
        ``seq > since_seq``, in order. Reads fresh handles — safe
        against the open append handle."""
        self.flush()
        for _, seg_path in self._segments():
            with open(seg_path, "rb") as f:
                if f.read(len(SEGMENT_MAGIC)) != SEGMENT_MAGIC:
                    return
                while True:
                    hdr = f.read(FRAME.size)
                    if len(hdr) < FRAME.size:
                        break
                    magic, seq, length, crc = FRAME.unpack(hdr)
                    if magic != FRAME_MAGIC:
                        return
                    payload = f.read(length)
                    if (len(payload) < length
                            or zlib.crc32(payload) & 0xFFFFFFFF != crc
                            or seq > self.last_seq):
                        return
                    if seq > since_seq:
                        meta, leaves = _parse_payload(payload)
                        yield seq, meta, leaves

    # ---- lifecycle -------------------------------------------------------

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def sync(self) -> None:
        """Force the barrier now regardless of policy (operator
        shutdown path)."""
        if self._f is not None and self._pending:
            self._f.flush()
            self._fsync(self._f)
            self._pending = 0

    def close(self) -> None:
        if self._f is not None:
            self.sync()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def fsync_honored(wal_factory, tmp_dir) -> bool:
    """The fsync-policy detector (the ``durability`` static-check
    section): build a WAL via ``wal_factory(dir, fsync='every_n',
    every_n=1)`` and count REAL ``os.fsync`` calls across three
    appends — the policy promises one barrier per append, so fewer
    than three means the WAL's fsync seam is lying (the
    ``analysis.fixtures.wal_skips_fsync`` broken twin must fail here).
    The count window also covers segment creation, so the threshold is
    a floor, not an equality."""
    import crdt_tpu.durability.wal as _wal_mod

    calls = 0
    real = os.fsync

    def counting(fd):
        nonlocal calls
        calls += 1
        return real(fd)

    d = os.path.join(os.fspath(tmp_dir), "fsync-probe")
    _wal_mod.os.fsync, saved = counting, _wal_mod.os.fsync
    try:
        w = wal_factory(d, fsync="every_n", every_n=1)
        base = calls
        for i in range(3):
            w.append({"rtype": "state", "kind": "probe"}, [np.arange(4)])
        w.close()
        return calls - base >= 3
    finally:
        _wal_mod.os.fsync = saved


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev("wal_fsync", subsystem="durability.wal",
        fields=("watermark", "bytes"), module=__name__)
_reg_ev("wal_torn_tail", subsystem="durability.wal",
        fields=("why", "at"), module=__name__)


__all__ = [
    "FRAME", "FRAME_MAGIC", "SEGMENT_MAGIC", "Wal", "WalCorrupt",
    "fsync_honored",
]
