"""Deterministic crash-point injection for the durability I/O paths.

A durability layer is only as crash-consistent as the WORST point a
process can die at, and "we fsync before rename" is a claim about
exactly those points. This module makes the claim testable: every
durability I/O boundary (the WAL's append/fsync/rotate steps, the
snapshot's write/rename/manifest/prune steps) registers a NAMED
crashpoint and calls :func:`hit` when execution crosses it. Normally
``hit`` is a counter tick; under :func:`armed` the named point raises
:class:`SimulatedCrash` ONCE — modelling a process killed mid-I/O with
everything already flushed to the OS durable, everything after the
point lost — and the fuzz loop (:func:`fuzz`) then runs recovery on
the surviving files and asserts bit-identity with the uninterrupted
run.

Design notes, stated plainly:

- **Crash = exception, flush = reached-the-OS.** An in-process
  "crash" cannot drop the page cache, so the simulation's fidelity
  contract is: bytes written BEFORE a crashpoint are flushed to the OS
  before ``hit`` is called (the WAL flushes before ``wal.mid_append``
  so the torn frame is really on disk), and nothing is written after
  the raise. What the simulation cannot model — a power loss eating
  OS-buffered-but-unfsynced pages — is covered statically instead: the
  fsync-policy detector (``wal.fsync_honored``) proves the fsync calls
  actually happen at the promised boundaries, and the no-fsync broken
  twin (``analysis.fixtures.wal_skips_fsync``) proves THAT detector
  fires.
- **One-shot arming.** A fired crashpoint disarms itself: recovery
  code crossing the same boundary (the torn-tail truncate is itself a
  write) must not crash again — the process restarted clean.
- **Registration is the coverage contract** (the registry discipline
  of analysis/registry.py): the ``durability`` static-check section
  runs the canonical micro-workload under :func:`recording` and fails
  if any registered crashpoint was never crossed — a dead crashpoint
  is an I/O boundary the fuzz loop silently stopped exercising.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.metrics import metrics


class SimulatedCrash(BaseException):
    """The process died at crashpoint ``name``. Deliberately NOT an
    ``Exception``: durability code paths that soften errors to
    counters (``except Exception``) must not absorb a simulated kill —
    a real SIGKILL would not be absorbable either."""

    def __init__(self, name: str):
        super().__init__(f"simulated crash at crashpoint {name!r}")
        self.name = name


_REGISTRY: Dict[str, str] = {}
_lock = threading.Lock()
_armed: Optional[str] = None
_recorded: Optional[set] = None


def register(name: str, description: str) -> str:
    """Register a named crashpoint (module import time, next to the
    I/O code that hits it). Re-registration with the same description
    is idempotent; with a different one it is a naming collision."""
    with _lock:
        if _REGISTRY.get(name, description) != description:
            raise ValueError(
                f"crashpoint {name!r} already registered with a different "
                f"description"
            )
        _REGISTRY[name] = description
    return name


def registered() -> Tuple[str, ...]:
    """Every registered crashpoint name, sorted (the fuzz matrix's
    first axis)."""
    with _lock:
        return tuple(sorted(_REGISTRY))


def describe(name: str) -> str:
    with _lock:
        return _REGISTRY[name]


def hit(name: str) -> None:
    """Cross crashpoint ``name``: record it, and die (once) if armed.
    Unregistered names refuse loudly — a typo here would silently
    excuse the boundary from the whole fuzz matrix."""
    global _armed
    with _lock:
        if name not in _REGISTRY:
            raise KeyError(f"crashpoint {name!r} was never registered")
        if _recorded is not None:
            _recorded.add(name)
        fire = _armed == name
        if fire:
            _armed = None  # one-shot: the restarted process runs clean
    if fire:
        metrics.count(f"durability.crashpoint_fired.{name}")
        raise SimulatedCrash(name)


@contextlib.contextmanager
def armed(name: str):
    """Arm crashpoint ``name`` for the block (one-shot: the first hit
    fires and disarms). Leaving the block always disarms — a workload
    that never crossed the armed point must not leak the arming into
    the next one."""
    global _armed
    if name not in _REGISTRY:
        raise KeyError(f"crashpoint {name!r} was never registered")
    with _lock:
        prev, _armed = _armed, name
    try:
        yield
    finally:
        with _lock:
            _armed = prev


@contextlib.contextmanager
def recording():
    """Collect the set of crashpoints crossed inside the block (the
    coverage-contract probe). Yields the live set."""
    global _recorded
    with _lock:
        prev, _recorded = _recorded, set()
        live = _recorded
    try:
        yield live
    finally:
        with _lock:
            _recorded = prev


def fuzz(
    crash_run: Callable[[str], object],
    recover: Callable[[], Tuple[object, object]],
    equal: Callable[[object, object], bool],
    names: Optional[Tuple[str, ...]] = None,
) -> List[str]:
    """The kill-then-recover loop — THE engine behind both real gates
    (the ``durability`` static-check section and
    tests/test_durability.py's diagonal/matrix): for each crashpoint,
    run ``crash_run(name)`` with the point armed (it must actually die
    there — a survivor means the workload no longer crosses the
    boundary), then ``recover()`` the surviving files; it returns
    ``(got, want)`` — the recovered state and what the caller's
    invariant says it must equal (typically the last DURABLE record,
    which depends on where the kill landed) — compared with ``equal``.
    Returns failure strings (empty = green). ``crash_run`` owns fresh
    directories per call (a closure/box shared with ``recover``) —
    this loop owns only the protocol."""
    failures: List[str] = []
    for name in names or registered():
        try:
            with armed(name):
                crash_run(name)
        except SimulatedCrash as crash:
            if crash.name != name:
                failures.append(
                    f"{name}: crashed at {crash.name!r} instead"
                )
                continue
        else:
            failures.append(
                f"{name}: workload never crossed the armed crashpoint "
                f"(boundary no longer exercised — fuzz hole)"
            )
            continue
        try:
            got, want = recover()
        except Exception as exc:
            failures.append(
                f"{name}: recovery failed: {type(exc).__name__}: {exc}"
            )
            continue
        if not equal(got, want):
            failures.append(
                f"{name}: recovered state is NOT bit-identical to the "
                f"last durable record"
            )
    return failures


__all__ = [
    "SimulatedCrash", "armed", "describe", "fuzz", "hit", "recording",
    "register", "registered",
]
