"""Generational atomic snapshots layered on ``crdt_tpu.checkpoint``.

One snapshot DIRECTORY holds K generations; each generation ``g`` is a
payload file plus a manifest, committed in a strict order that gives
every crash window a defined meaning:

1. payload bytes → ``.tmp-payload-<g>`` (crash: no generation exists);
2. payload fsync, then ``os.replace`` → ``gen-<g>.npz`` (crash: a
   payload without a manifest — NOT a generation, ignored by load);
3. manifest JSON (per-array content checksums —
   ``checkpoint.array_checksum`` — payload byte length + whole-file
   CRC, the WAL watermark ``wal_seq``, payload kind) →
   ``.tmp-manifest-<g>``, fsync, ``os.replace`` → ``gen-<g>.json``
   — THE COMMIT POINT;
4. directory fsync, then prune generations older than ``retain``
   (crash mid-prune: extra old generations, harmless).

``load_newest`` walks generations newest-first and takes the first
VALID one — manifest parses, payload present, every checksum matches —
counting ``durability.snapshot_fallback`` for each corrupt generation
it skips; recovery then replays a LONGER WAL suffix (the older
generation's ``wal_seq``) instead of failing. Two payload kinds:

- ``model`` — any ``checkpoint``-able model (``checkpoint._dump`` /
  ``_restore``; ``compact=True`` composes exactly like
  ``checkpoint.save(compact=True)``);
- ``state`` — a raw mesh state pytree (numbered leaves; loading needs
  a congruent ``template`` to unflatten through — the caller that
  resumes a mesh knows its shapes).

Crashpoints (``durability.crashpoints``) bracket every boundary; the
fuzz loop kills at each and recovery must land bit-identical.
"""

from __future__ import annotations

import json
import os
import re
import zlib
from typing import List, NamedTuple, Optional, Tuple

import jax
import numpy as np

from ..checkpoint import (
    array_checksum,
    from_npz_bytes,
    fsync_dir,
    to_npz_bytes,
    _dump,
    _restore,
)
from .. import obs
from ..utils.metrics import metrics
from . import crashpoints as cp

_GEN_RE = re.compile(r"^gen-(\d{8})\.json$")

CP_PRE_WRITE = cp.register(
    "snapshot.pre_write", "before any payload byte is written"
)
CP_MID_WRITE = cp.register(
    "snapshot.mid_snapshot_write",
    "half the payload flushed to the tmp file — a torn snapshot",
)
CP_POST_WRITE_PRE_FSYNC = cp.register(
    "snapshot.post_write_pre_fsync",
    "payload fully flushed, fsync barrier not yet issued",
)
CP_PRE_RENAME = cp.register(
    "snapshot.pre_rename", "payload fsynced, still under the tmp name"
)
CP_POST_RENAME_PRE_MANIFEST = cp.register(
    "snapshot.post_rename_pre_manifest",
    "payload renamed into place, manifest (the commit point) absent",
)
CP_MID_MANIFEST = cp.register(
    "snapshot.mid_manifest_write",
    "half the manifest flushed to the tmp file",
)
CP_PRE_MANIFEST_RENAME = cp.register(
    "snapshot.pre_manifest_rename",
    "manifest fsynced, still under the tmp name — one rename from commit",
)
CP_POST_COMMIT_PRE_PRUNE = cp.register(
    "snapshot.post_commit_pre_prune",
    "generation committed, retain-K prune not yet run",
)
CP_MID_PRUNE = cp.register(
    "snapshot.mid_prune", "one old generation unlinked, others pending"
)


class SnapshotCorrupt(RuntimeError):
    """No VALID generation survives in the snapshot directory (every
    manifest/payload pair is damaged, or none was ever committed)."""


class Generation(NamedTuple):
    gen: int
    wal_seq: int
    payload_kind: str       # "model" | "state"
    merge_kind: str         # registry merge kind ("" for model payloads)


def _gen_paths(path, gen: int) -> Tuple[str, str]:
    d = os.fspath(path)
    return (
        os.path.join(d, f"gen-{gen:08d}.npz"),
        os.path.join(d, f"gen-{gen:08d}.json"),
    )


def generations(path) -> List[int]:
    """Committed generation numbers (manifest present), ascending."""
    try:
        names = os.listdir(os.fspath(path))
    except OSError:
        return []
    return sorted(int(m.group(1)) for n in names if (m := _GEN_RE.match(n)))


def _write_payload_and_manifest(
    path, gen: int, raw: bytes, manifest: dict, retain: int,
) -> int:
    """Steps 1-4 of the commit protocol (module docstring)."""
    d = os.fspath(path)
    os.makedirs(d, exist_ok=True)
    payload_path, manifest_path = _gen_paths(path, gen)
    tmp_payload = os.path.join(d, f".tmp-payload-{gen:08d}")
    tmp_manifest = os.path.join(d, f".tmp-manifest-{gen:08d}")

    cp.hit(CP_PRE_WRITE)
    with open(tmp_payload, "wb") as f:
        half = len(raw) // 2
        f.write(raw[:half])
        f.flush()  # the torn half really reached the OS (crash model)
        cp.hit(CP_MID_WRITE)
        f.write(raw[half:])
        f.flush()
        cp.hit(CP_POST_WRITE_PRE_FSYNC)
        os.fsync(f.fileno())
    cp.hit(CP_PRE_RENAME)
    os.replace(tmp_payload, payload_path)
    fsync_dir(d)
    cp.hit(CP_POST_RENAME_PRE_MANIFEST)

    mraw = json.dumps(manifest, sort_keys=True).encode("utf-8")
    with open(tmp_manifest, "wb") as f:
        half = len(mraw) // 2
        f.write(mraw[:half])
        f.flush()
        cp.hit(CP_MID_MANIFEST)
        f.write(mraw[half:])
        f.flush()
        os.fsync(f.fileno())
    cp.hit(CP_PRE_MANIFEST_RENAME)
    os.replace(tmp_manifest, manifest_path)  # THE commit point
    fsync_dir(d)
    metrics.count("durability.snapshots_written")
    obs.emit("snapshot_commit", gen=gen,
             wal_seq=manifest.get("wal_seq", 0))
    cp.hit(CP_POST_COMMIT_PRE_PRUNE)

    gens = generations(path)
    stale = gens[:-retain] if retain > 0 else []
    for i, old in enumerate(stale):
        p_old, m_old = _gen_paths(path, old)
        # Manifest first: a crash mid-prune must never leave a
        # manifest pointing at an unlinked payload looking "corrupt" —
        # a missing manifest just means "not a generation".
        for victim in (m_old, p_old):
            try:
                os.unlink(victim)
            except OSError:
                pass
        if i == 0:
            cp.hit(CP_MID_PRUNE)
    if stale:
        fsync_dir(d)
    return gen


def _manifest_for(raw: bytes, arrays: dict, *, wal_seq: int,
                  payload_kind: str, merge_kind: str) -> dict:
    return {
        "version": 1,
        "payload": payload_kind,
        "kind": merge_kind,
        "wal_seq": int(wal_seq),
        "payload_bytes": len(raw),
        "payload_crc32": zlib.crc32(raw) & 0xFFFFFFFF,
        "checksums": {k: array_checksum(v) for k, v in arrays.items()},
    }


def save(path, model, *, wal_seq: int = 0, retain: int = 3,
         compact: bool = False) -> int:
    """Commit a new generation holding a checkpointable MODEL; returns
    its generation number. ``wal_seq`` is the WAL watermark the payload
    includes (replay starts after it); ``compact=True`` composes
    ``checkpoint.save``'s compact-on-save; ``retain`` keeps the newest
    K generations (older ones prune after commit)."""
    if compact:
        from .. import elastic
        from ..reclaim import compact_model

        try:
            elastic.kind_of(model)
        except TypeError:
            metrics.count("reclaim.compact_on_save_unsupported")
        else:
            compact_model(model)
    meta, arrays = _dump(model)
    raw = to_npz_bytes(meta, arrays)
    gen = (generations(path) or [0])[-1] + 1
    manifest = _manifest_for(
        raw, arrays, wal_seq=wal_seq, payload_kind="model", merge_kind="",
    )
    return _write_payload_and_manifest(path, gen, raw, manifest, retain)


def save_state(path, kind: str, state, *, wal_seq: int = 0,
               retain: int = 3) -> int:
    """Commit a new generation holding a RAW mesh state pytree of
    registered merge ``kind`` (numbered leaves; ``load_newest`` needs a
    congruent template to unflatten)."""
    arrays = {
        f"a_{i}": np.asarray(x)
        for i, x in enumerate(jax.tree.leaves(state))
    }
    raw = to_npz_bytes({"payload": "state", "kind": kind}, arrays)
    gen = (generations(path) or [0])[-1] + 1
    manifest = _manifest_for(
        raw, arrays, wal_seq=wal_seq, payload_kind="state", merge_kind=kind,
    )
    return _write_payload_and_manifest(path, gen, raw, manifest, retain)


def _load_generation(path, gen: int, template=None):
    """One generation's ``(payload, Generation)`` — raises on ANY
    integrity failure (the caller falls back a generation)."""
    payload_path, manifest_path = _gen_paths(path, gen)
    with open(manifest_path, "rb") as f:
        mraw = f.read()
    try:
        manifest = json.loads(mraw.decode("utf-8"))
    except ValueError as exc:  # torn/garbled manifest IS corruption —
        # it must fall back a generation, not escape as a caller error
        raise SnapshotCorrupt(
            f"generation {gen}: manifest does not parse ({exc})"
        )
    with open(payload_path, "rb") as f:
        raw = f.read()
    if (len(raw) != int(manifest["payload_bytes"])
            or zlib.crc32(raw) & 0xFFFFFFFF != int(manifest["payload_crc32"])):
        raise SnapshotCorrupt(
            f"generation {gen}: payload bytes fail the manifest CRC"
        )
    meta, arrays = from_npz_bytes(payload_path, raw)  # npz-level checksums
    sums = manifest.get("checksums", {})
    for name, v in arrays.items():
        if array_checksum(v) != int(sums.get(name, -1)):
            raise SnapshotCorrupt(
                f"generation {gen}: array {name!r} fails its manifest "
                f"checksum"
            )
    info = Generation(
        gen=gen,
        wal_seq=int(manifest["wal_seq"]),
        payload_kind=manifest["payload"],
        merge_kind=manifest.get("kind", ""),
    )
    if info.payload_kind == "model":
        return _restore(meta, arrays), info
    if template is None:
        raise ValueError(
            "state-payload generation needs a congruent `template` to "
            "unflatten through"
        )
    n = sum(1 for k in arrays if k.startswith("a_"))
    leaves = [jax.device_put(arrays[f"a_{i}"]) for i in range(n)]
    return (
        jax.tree.unflatten(jax.tree.structure(template), leaves),
        info,
    )


def load_newest(path, template=None):
    """The newest VALID generation's ``(payload, Generation)`` —
    corrupt generations fall back one at a time (counting
    ``durability.snapshot_fallback`` each; the recovery driver then
    replays the older generation's longer WAL suffix). Raises
    :class:`SnapshotCorrupt` when nothing valid survives."""
    gens = generations(path)
    last_err: Optional[BaseException] = None
    for gen in reversed(gens):
        try:
            return _load_generation(path, gen, template)
        except (ValueError, TypeError):
            raise  # caller bugs (missing template) are not corruption
        except Exception as exc:
            metrics.count("durability.snapshot_fallback")
            obs.emit("snapshot_fallback", gen=gen)
            last_err = exc
    raise SnapshotCorrupt(
        f"no valid generation in {os.fspath(path)!r} "
        f"(saw {gens or 'none'}; last error: {last_err})"
    )


def corrupt_generation(path, gen: int) -> None:
    """Rot one generation's payload in the way only the MANIFEST can
    catch: perturb an array and re-serialize the npz so the file stays
    internally parseable (a naive byte-flip would trip the zip layer's
    own entry CRC and even a checksum-blind loader would "detect" it —
    masking the gate). The manifest's recorded checksums / payload CRC
    are left stale, exactly the cross-file inconsistency a torn
    replacement or buggy re-writer produces."""
    import io
    import json

    payload_path, _ = _gen_paths(path, gen)
    with open(payload_path, "rb") as f:
        raw = f.read()
    with np.load(io.BytesIO(raw)) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        arrays = {k: np.array(z[k]) for k in z.files if k != "meta"}
    name = sorted(k for k in arrays if k != "meta")[0]
    flat = arrays[name].reshape(-1)
    if flat.size:
        flat[0] = np.bitwise_xor(
            flat[0], np.ones((), flat.dtype)
        ) if flat.dtype.kind in "iu" else flat[0] + 1
    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(
            json.dumps(meta, sort_keys=True).encode("utf-8"),
            dtype=np.uint8,
        ),
        **arrays,
    )
    with open(payload_path, "wb") as f:
        f.write(buf.getvalue())


def loader_detects_corruption(load_fn) -> bool:
    """The loader-integrity detector (the ``durability`` static-check
    section): commit a single-generation snapshot into a scratch dir,
    rot its payload (:func:`corrupt_generation`), and require
    ``load_fn(dir, template)`` to REFUSE (any exception). The
    checksum-ignoring broken twin
    (``analysis.fixtures.snapshot_load_unchecked``) must fail here —
    it would hand rotten state to a resuming mesh."""
    import tempfile

    import jax.numpy as jnp

    state = {"a": jnp.arange(64, dtype=jnp.uint32)}
    with tempfile.TemporaryDirectory() as d:
        gen = save_state(d, "probe", state, wal_seq=0)
        corrupt_generation(d, gen)
        try:
            load_fn(d, state)
        except Exception:
            return True
        return False


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev("snapshot_commit", subsystem="durability.snapshot",
        fields=("gen", "wal_seq"), module=__name__)
_reg_ev("snapshot_fallback", subsystem="durability.snapshot",
        fields=("gen",), module=__name__)


__all__ = [
    "Generation", "SnapshotCorrupt", "corrupt_generation", "generations",
    "load_newest", "loader_detects_corruption", "save", "save_state",
]
