"""Crash recovery: newest valid generation + WAL-suffix replay.

The recovery invariant the crashpoint fuzz pins: for ANY kill point in
the durability I/O, ``recover_state`` (or ``recover_model``) applied
to the surviving files yields exactly the last state whose WAL record
was durable — bit-identically — and the caller resumes from there.
Mechanics:

1. ``snapshot.load_newest`` walks generations newest-first; a corrupt
   newest generation FALLS BACK one generation (the older manifest's
   smaller ``wal_seq`` just means a longer replay suffix) —
   ``durability.snapshot_fallback`` counts each skip;
2. the WAL suffix (``seq > generation.wal_seq``) replays through ONE
   memoised jitted scan-fold per (kind, shape signature) — the
   ``delta_opt/heal.py`` dispatch-collapse pattern: however many δ
   records the suffix holds, the host issues one program, not one
   dispatch per record. Positional reconstruction is exact
   (``decompose.reconstruct`` — the reconstruction law), so every
   replayed record lands the logged post-state bit-identically;
   full-``state`` records (elastic-widen fallbacks) adopt wholesale
   and re-anchor the scan at the new shapes.

Rejoin (:func:`rejoin`) is the membership-contract upgrade this
enables (crdt_tpu/faults/membership.py): a restarted rank recovers
LOCALLY from snapshot + log — no network — and the live peer then
ships only its join-irreducible decomposition over the recovered state
instead of a full state; reconstruction is bit-exact regardless of
whether the recovered state is a true lower bound (the positional
diff is unconditional — heal.py's argument), and the final join keeps
any recovered-but-unreplicated local content. ``bench.py --recovery``
measures the byte win (< 25% of full-state resync is the acceptance
gate).
"""

from __future__ import annotations

import functools
import time
from typing import Any, NamedTuple, Optional, Tuple

import jax
import numpy as np

from .. import obs
from ..utils.metrics import metrics, state_nbytes
from . import snapshot as snap
from .snapshot import SnapshotCorrupt
from .wal import Wal


def _record_recovery(report: "RecoveryReport") -> None:
    """A completed recovery is a postmortem boundary BY DEFINITION —
    something died to need one. Record the event and auto-dump the
    flight artifact (obs/recorder.py; a no-op when no recorder is
    installed)."""
    obs.emit(
        "recovery", generation=report.generation,
        wal_seq_start=report.wal_seq_start,
        replayed=report.replayed_records,
        fallbacks=report.snapshot_fallbacks,
    )
    obs.auto_dump("recovery", generation=report.generation)


class RecoveryReport(NamedTuple):
    """One recovery pass's accounting."""

    generation: int           # generation loaded (0 = none, base used)
    wal_seq_start: int        # replay started after this seq
    replayed_records: int     # δ + full-state records replayed
    full_state_records: int   # of those, widen-fallback full states
    snapshot_fallbacks: int   # corrupt generations skipped
    seconds: float


class RejoinReport(NamedTuple):
    """Byte accounting for one log-suffix rejoin (vs full-state)."""

    lanes_shipped: int
    bytes_shipped: float      # decomposition payload over the wire
    bytes_full_state: float   # what full-state resync would ship
    ratio: float              # shipped / full — the headline quantity


@functools.lru_cache(maxsize=None)
def _replay_scan(kind: str, batched: bool):
    """One jitted scan-fold per (kind, batching): reconstruct every
    record of a homogeneous run in a single program (module docstring).
    jit re-traces per new shape signature; the lru keys the closure."""
    from ..analysis.registry import get_decomposer
    from ..delta_opt.decompose import reconstruct

    dec = get_decomposer(kind)

    def recon(s, d):
        return reconstruct(dec, s, d)

    @jax.jit
    def replay(state, stack):
        def body(s, d):
            if batched:
                return jax.vmap(recon)(s, d), None
            return recon(s, d), None
        out, _ = jax.lax.scan(body, state, stack)
        return out

    return replay


def _decomp_treedef(kind: str, state, batched: bool):
    """The treedef a δ record's leaves unflatten through — derived by
    ``eval_shape`` (no compute) of the decomposition of ``state`` over
    itself."""
    from ..delta_opt.decompose import decompose

    if batched:
        fn = lambda: jax.vmap(lambda s: decompose(kind, s, s))(state)
    else:
        fn = lambda: decompose(kind, state, state)
    return jax.tree.structure(jax.eval_shape(fn))


def replay(wal: Wal, state, kind: Optional[str] = None,
           since_seq: int = 0) -> Tuple[Any, int, int]:
    """Replay the WAL suffix ``seq > since_seq`` onto ``state``;
    returns ``(state, replayed_records, full_state_records)``.
    ``resume`` records are stream bookkeeping, not state transitions —
    skipped here (``load_stream_resume`` reads them)."""
    n_replayed = 0
    n_full = 0
    run: list = []           # homogeneous δ-record leaf lists
    run_sig = None           # (kind, batched, shapes) of the open run

    def flush_run(state):
        nonlocal run, run_sig
        if not run:
            return state
        rkind, batched, _ = run_sig
        treedef = _decomp_treedef(rkind, state, batched)
        stack = jax.tree.unflatten(
            treedef,
            [
                jax.device_put(np.stack([leaves[i] for leaves in run]))
                for i in range(len(run[0]))
            ],
        )
        state = _replay_scan(rkind, batched)(state, stack)
        run, run_sig = [], None
        return state

    for seq, meta, leaves in wal.records(since_seq):
        rtype = meta.get("rtype")
        if rtype == "resume":
            continue
        rkind = meta.get("kind")
        if kind is None:
            kind = rkind
        elif rkind != kind:
            raise RuntimeError(
                f"WAL record {seq} is kind {rkind!r}, replay is for "
                f"{kind!r} — one log per object (use separate WAL dirs)"
            )
        if rtype == "state":
            # Widen-fallback full state: adopt wholesale; the scan
            # re-anchors at the new shapes on the next δ run.
            state = flush_run(state)
            state = jax.tree.unflatten(
                jax.tree.structure(state),
                [jax.device_put(x) for x in leaves],
            )
            n_full += 1
            n_replayed += 1
            continue
        sig = (rkind, bool(meta.get("batched", True)),
               tuple((x.shape, str(x.dtype)) for x in leaves))
        if run and sig != run_sig:
            state = flush_run(state)
        run_sig = sig
        run.append(leaves)
        n_replayed += 1
    state = flush_run(state)
    jax.block_until_ready(jax.tree.leaves(state))
    metrics.count("durability.replayed_records", n_replayed)
    return state, n_replayed, n_full


def recover_state(
    snap_dir, wal: Wal, template, kind: Optional[str] = None,
    default=None,
):
    """Recover a raw mesh state: newest valid generation (falling back
    past corrupt ones) + WAL-suffix replay. ``template`` unflattens
    state-payload generations (the resuming caller knows its shapes);
    ``default`` is the genesis state when NO generation was ever
    committed (the log then replays from seq 0) — without it that case
    raises :class:`SnapshotCorrupt`. Returns ``(state, report)``."""
    t0 = time.perf_counter()
    fallbacks = 0
    try:
        payload, info = snap.load_newest(snap_dir, template)
        gens = snap.generations(snap_dir)
        fallbacks = len([g for g in gens if g > info.gen])
        state, since = payload, info.wal_seq
        gen = info.gen
        if kind is None and info.merge_kind:
            kind = info.merge_kind
    except SnapshotCorrupt:
        if default is None:
            raise
        state, since, gen = default, 0, 0
        fallbacks = len(snap.generations(snap_dir))
    state, n_replayed, n_full = replay(wal, state, kind, since)
    metrics.count("durability.recovery_rounds")
    report = RecoveryReport(
        generation=gen,
        wal_seq_start=since,
        replayed_records=n_replayed,
        full_state_records=n_full,
        snapshot_fallbacks=fallbacks,
        seconds=time.perf_counter() - t0,
    )
    _record_recovery(report)
    return state, report


def recover_model(snap_dir, wal: Wal, kind: Optional[str] = None):
    """Recover a checkpointable MODEL (model-payload generations): the
    restored model's ``.state`` replays the WAL suffix in place. The
    merge ``kind`` defaults to ``elastic.kind_of(model)``. Returns
    ``(model, report)``."""
    t0 = time.perf_counter()
    model, info = snap.load_newest(snap_dir)
    gens = snap.generations(snap_dir)
    fallbacks = len([g for g in gens if g > info.gen])
    if kind is None:
        from .. import elastic

        kind = elastic.kind_of(model)
    state, n_replayed, n_full = replay(wal, model.state, kind, info.wal_seq)
    model.state = state
    metrics.count("durability.recovery_rounds")
    report = RecoveryReport(
        generation=info.gen,
        wal_seq_start=info.wal_seq,
        replayed_records=n_replayed,
        full_state_records=n_full,
        snapshot_fallbacks=fallbacks,
        seconds=time.perf_counter() - t0,
    )
    _record_recovery(report)
    return model, report


def load_stream_resume(wal: Wal, template):
    """The newest stream resume point ``(acc, blocks_done)`` persisted
    by ``mesh_stream_fold*(wal=...)`` — or ``None`` when the log holds
    no resume record. ``blocks_done`` is ABSOLUTE in the original
    source (resumed runs compose via ``wal_base=``): re-enter the
    stream with ``init=acc``, the source re-chunked from
    ``blocks_done``, and ``wal_base=blocks_done`` so a further kill
    still resumes at the true position (the ``StreamInterrupted``
    contract, made durable)."""
    found = None
    for _, meta, leaves in wal.records(0):
        if meta.get("rtype") == "resume":
            found = (meta, leaves)
    if found is None:
        return None
    meta, leaves = found
    acc = jax.tree.unflatten(
        jax.tree.structure(template), [jax.device_put(x) for x in leaves]
    )
    return acc, int(meta["blocks_done"])


def rejoin(kind: str, live_state, recovered_state):
    """Log-suffix rejoin of one restarted rank against one live peer
    (module docstring): the peer ships ``decompose(live, recovered)``
    — only the divergence lanes — reconstruction lands the peer's
    state bit-exactly, and the final join keeps any recovered-but-
    unreplicated local content. Returns ``(healed, RejoinReport)``;
    counters ``durability.rejoin_bytes_shipped`` / ``_full``."""
    from ..analysis.registry import get_merge_kind
    from ..delta_opt.decompose import (
        decompose, decomposition_bytes, reconstruct,
    )

    d = decompose(kind, live_state, recovered_state)
    shipped = float(decomposition_bytes(d))
    recon = reconstruct(kind, recovered_state, d)  # == live, bit-exact
    mk = get_merge_kind(kind)
    out = mk.join(recon, recovered_state)
    healed = out[0] if isinstance(out, tuple) and len(out) == 2 else out
    full = float(state_nbytes(live_state))
    metrics.count("durability.rejoin_bytes_shipped", int(shipped))
    metrics.count("durability.rejoin_bytes_full", int(full))
    return healed, RejoinReport(
        lanes_shipped=int(jax.numpy.sum(d.valid)),
        bytes_shipped=shipped,
        bytes_full_state=full,
        ratio=shipped / full if full else 0.0,
    )


from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev("recovery", subsystem="durability.recover",
        fields=("generation", "wal_seq_start", "replayed", "fallbacks"),
        module=__name__)


__all__ = [
    "RecoveryReport", "RejoinReport", "load_stream_resume",
    "recover_model", "recover_state", "rejoin", "replay",
]
