"""In-kernel fixed-bucket (log2) histograms — distributions that
survive jit/shard_map.

The registry's gauges (utils/metrics.py) keep last/min/max/sum/n — no
shape of the distribution, so a p99 apply latency (the ROADMAP item-1
serving gate) is unmeasurable. This module is the lax-only primitive
that fixes it: a :class:`Hist` is one ``uint32[NBUCKETS]`` counter
plane plus a float32 running total, observed with pure ``jnp`` ops on
static shapes, so it rides the :class:`crdt_tpu.telemetry.Telemetry`
sidecar through jit and shard_map exactly like the scalar counters,
psums across the mesh like them, and folds across runs with
``telemetry.combine``.

Buckets are powers of two with INCLUSIVE upper edges ``EDGES = (1, 2,
4, ..., 2**(NBUCKETS-2))``: bucket 0 holds values in ``[0, 1]``,
bucket ``i`` holds ``(2**(i-1), 2**i]``, and the last bucket is
unbounded (the Prometheus ``+Inf`` bucket). Right-closed buckets are
the Prometheus ``le`` contract — a sample exactly equal to an edge
counts under that edge's ``le`` label — so the exporter's
``_bucket{le=...}`` exposition is conformant without relabeling. The
bucket index is computed by EXACT comparison against the edge vector —
no ``log2`` rounding at the boundaries, so the host replay of an
in-kernel fold is bit-identical (the ``histogram_miscounts`` broken
twin in analysis/fixtures.py proves the conformance detector notices
anything less).

Units are the observer's contract, chosen so log2 buckets resolve the
interesting range: the δ ring observes per-round backlog ROWS and
payload BYTES; host dispatch timing observes MICROSECONDS (a sub-µs
dispatch is bucket 0; 2**30 µs ≈ 18 min caps the top bucket).

Quantile summaries (:func:`summary` — p50/p95/p99 by linear
interpolation within the covering bucket) are host-side; the exporter
renders the same counts as Prometheus ``_bucket``/``_sum``/``_count``
exposition and ``tools/obs_report.py`` folds dumped counts bit-exactly
against the live registry.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Sequence

import jax
import jax.numpy as jnp

NBUCKETS = 32

# Finite upper edges (NBUCKETS - 1 of them); the last bucket is +Inf.
EDGES = tuple(float(2 ** i) for i in range(NBUCKETS - 1))


class Hist(NamedTuple):
    """One log2 histogram: a counter plane + the exact running total
    of observed values (so Prometheus ``_sum`` is exact, not a
    bucket-midpoint estimate)."""

    counts: jax.Array  # uint32[NBUCKETS]
    total: jax.Array   # float32 — sum of observed values


def zeros() -> Hist:
    """The accumulation identity."""
    return Hist(
        counts=jnp.zeros((NBUCKETS,), jnp.uint32),
        total=jnp.zeros((), jnp.float32),
    )


def bucket_index(value) -> jax.Array:
    """The bucket covering ``value`` (scalar, int32): exact edge
    comparisons — ``sum(value > edge)`` — never a floating log2, so
    boundary values land deterministically and on the Prometheus
    ``le`` side (2.0 is in (1, 2], counted under ``le="2"``).
    Negative values clamp into bucket 0."""
    v = jnp.asarray(value).astype(jnp.float32)
    e = jnp.asarray(EDGES, jnp.float32)
    return jnp.sum(v > e, dtype=jnp.int32)


def observe(h: Hist, value) -> Hist:
    """Count one observation (lax-only: one scatter-add on a static
    shape — safe inside jit, shard_map, and ``lax.fori_loop``
    carries)."""
    v = jnp.maximum(jnp.asarray(value).astype(jnp.float32), 0.0)
    return Hist(
        counts=h.counts.at[bucket_index(v)].add(jnp.uint32(1)),
        total=h.total + v,
    )


def observe_vec(h: Hist, values, mask=None) -> Hist:
    """Count a whole vector of observations in ONE scatter-add (the
    fan-out dispatch's per-cohort push-bytes path — B lanes per call,
    so a per-lane ``observe`` loop would unroll B scatters into the
    traced program). ``mask`` selects which lanes count (False lanes
    contribute nothing — the empty-dispatch-lane convention). Bucket
    indices use the same exact edge comparisons as
    :func:`bucket_index`, so a host replay folds bit-identically."""
    v = jnp.maximum(jnp.asarray(values).astype(jnp.float32), 0.0)
    m = (
        jnp.ones(v.shape, bool) if mask is None
        else jnp.asarray(mask, bool)
    )
    e = jnp.asarray(EDGES, jnp.float32)
    idx = jnp.sum(v[:, None] > e[None, :], axis=-1, dtype=jnp.int32)
    return Hist(
        counts=h.counts.at[idx].add(m.astype(jnp.uint32)),
        total=h.total + jnp.sum(jnp.where(m, v, 0.0)),
    )


def merge(a: Hist, b: Hist) -> Hist:
    """Fold two histograms (counts and totals both add — the
    ``telemetry.combine`` discipline for distribution fields)."""
    return Hist(counts=a.counts + b.counts, total=a.total + b.total)


def psum(h: Hist, axes) -> Hist:
    """Mesh-reduce a per-device histogram into a replicated one
    (inside shard_map) — counts and total both psum, like the scalar
    throughput counters."""
    from jax import lax

    return Hist(counts=lax.psum(h.counts, axes), total=lax.psum(h.total, axes))


def is_hist_field(name: str) -> bool:
    """The Telemetry field-naming contract: ``hist_*`` fields carry a
    :class:`Hist` subtree (telemetry.py / exporter.py / the schema all
    key on this prefix)."""
    return name.startswith("hist_")


def to_dict(h: Hist) -> Dict[str, Any]:
    """The self-describing JSONL form (tools/telemetry_schema.json
    ``histogram`` kind): finite bucket edges + counts (one longer —
    the trailing count is the unbounded bucket) + the exact total."""
    return {
        "edges": list(EDGES),
        "counts": [int(c) for c in h.counts],
        "total": float(h.total),
    }


def quantile(counts: Sequence[int], q: float,
             edges: Sequence[float] = EDGES) -> float:
    """Estimate the q-quantile (0 < q <= 1) from folded bucket counts:
    find the covering bucket by cumulative rank, interpolate linearly
    inside it. The unbounded top bucket reports twice its lower edge
    (there is no upper edge to interpolate toward). 0.0 on an empty
    histogram."""
    n = int(sum(counts))
    if n <= 0:
        return 0.0
    target = q * n
    cum = 0
    for i, c in enumerate(counts):
        prev = cum
        cum += int(c)
        if cum >= target and c:
            lo = 0.0 if i == 0 else float(edges[i - 1])
            hi = float(edges[i]) if i < len(edges) else 2.0 * float(edges[-1])
            frac = (target - prev) / c
            return lo + frac * (hi - lo)
    return float(edges[-1]) * 2.0


def summary(d: Dict[str, Any]) -> Dict[str, float]:
    """p50/p95/p99 + count/total/mean from one :func:`to_dict` payload
    — the shape the registry gauges and the BENCH records carry."""
    counts = d["counts"]
    edges = d.get("edges", EDGES)
    n = int(sum(counts))
    total = float(d.get("total", 0.0))
    return {
        "count": n,
        "total": total,
        "mean": (total / n) if n else 0.0,
        "p50": quantile(counts, 0.50, edges),
        "p95": quantile(counts, 0.95, edges),
        "p99": quantile(counts, 0.99, edges),
    }


__all__ = [
    "EDGES", "Hist", "NBUCKETS", "bucket_index", "is_hist_field",
    "merge", "observe", "observe_vec", "psum", "quantile", "summary",
    "to_dict",
    "zeros",
]
