"""Flight recorder: a bounded host-side ring of per-round structured
events, dumped as a postmortem artifact when a run goes wrong.

End-of-run counter totals (the registry) say WHAT happened; they
cannot say in what ORDER — which round lost packets, whether the
eviction preceded or followed the WAL watermark, whether the
autoscaler voted before the drain refused. The recorder keeps that
sequence: every subsystem emits small structured events (telemetry
snapshot deltas, fault draws/rejections, membership suspicion and
eviction, scale-out generation changes, WAL watermarks and fsyncs,
snapshot commits, elastic widen/shrink votes) into one process-global
bounded ring, each stamped with the monotonic correlation key
``(generation, round, rank)``:

- ``generation`` — the scale-out membership generation
  (``ScaleoutMesh`` bumps it on every admit/drain ring rebuild);
- ``round``      — a host-side dispatch counter (one mesh entry-point
  dispatch = one anti-entropy round from the host's point of view;
  the in-kernel rounds of one dispatch are a single event);
- ``rank``       — the emitting host/process rank (0 on single-host).

``telemetry.span`` stamps the SAME key onto its trace events, so
device-side spans and host-side I/O line up on one timeline in the
dump and in ``tools/obs_report.py``'s rendering of it.

:meth:`FlightRecorder.dump` writes a self-describing JSONL artifact —
a header carrying the registered event-type schemas
(``analysis.registry.register_obs_event`` — registration is the
coverage contract, enforced by the ``obs`` static-check section), the
events, and a final registry snapshot that ``tools/obs_report.py``
cross-checks bit-exactly against the folded events. Dumps are
auto-invoked at the failure boundaries (``DrainRefused``,
``DcnExchangeFailed``, a non-empty ``StreamFaultReport``, recovery) —
:func:`auto_dump` — so the artifact exists precisely when someone
will need it.

The ring drops OLDEST events when full and counts every drop — both
in total (``dropped`` / the ``obs.events_dropped`` registry counter)
and PER EVENT TYPE (``dropped_by_type`` / the
``obs.events_dropped.<etype>`` registry twins, carried in every dump
header): under an 8k-event serve flood the postmortem question is not
"how many events were lost" but "WHICH KIND was lost" — a header
saying 5k ``trace_stage`` drops but zero ``tenant_evicted`` drops
means the eviction timeline is still trustworthy. A silent drop is
itself a bug class (the ``recorder_drops_events`` broken twin in
analysis/fixtures.py proves the conformance detector fires).

No recorder is installed by default — every ``emit`` is then a cheap
no-op, so instrumented subsystems cost nothing un-observed.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.metrics import metrics

FORMAT_VERSION = 1
DEFAULT_CAPACITY = 4096


class FlightRecorder:
    """The bounded event ring. Thread-safe; one per process is the
    normal deployment (:func:`install`), but tests construct private
    ones freely."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, *, rank: int = 0,
                 clock=time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._clock = clock
        self.dropped = 0
        self.dropped_by_type: Dict[str, int] = {}
        self._generation = 0
        self._round = 0
        self._rank = int(rank)
        self._base_snapshot = metrics.snapshot()

    # ---- the correlation key --------------------------------------------

    @property
    def generation(self) -> int:
        return self._generation

    @property
    def round_no(self) -> int:
        return self._round

    @property
    def rank(self) -> int:
        return self._rank

    def key(self) -> Tuple[int, int, int]:
        """The current ``(generation, round, rank)`` correlation key —
        stamped onto every event AND onto ``telemetry.span`` trace
        events, so device spans and host I/O share one timeline."""
        with self._lock:
            return (self._generation, self._round, self._rank)

    def set_generation(self, generation: int) -> None:
        """Adopt a membership generation (``ScaleoutMesh`` calls this
        on every ring rebuild). Monotonic: a stale generation is
        ignored rather than rewinding the key."""
        with self._lock:
            self._generation = max(self._generation, int(generation))

    def set_rank(self, rank: int) -> None:
        with self._lock:
            self._rank = int(rank)

    def advance_round(self, n: int = 1) -> int:
        """Advance the host-side round counter (one mesh dispatch =
        one round); returns the new round number."""
        with self._lock:
            self._round += int(n)
            return self._round

    # ---- recording -------------------------------------------------------

    def record(self, etype: str, **fields) -> dict:
        """Append one structured event, stamped ``(gen, round, rank)``
        and wall-clock. Returns the event dict. Oldest events drop
        when the ring is full (counted — never silent, and broken out
        PER EVENT TYPE so a postmortem can tell WHAT was lost)."""
        event = {
            "record": "flight",
            "type": str(etype),
            "ts": self._clock(),
        }
        lost: List[str] = []
        with self._lock:
            event["gen"] = self._generation
            event["round"] = self._round
            event["rank"] = self._rank
            event.update(fields)
            self._events.append(event)
            over = len(self._events) - self.capacity
            if over > 0:
                lost = [e.get("type", "?") for e in self._events[:over]]
                del self._events[:over]
                self.dropped += over
                for t in lost:
                    self.dropped_by_type[t] = (
                        self.dropped_by_type.get(t, 0) + 1
                    )
        metrics.count("obs.events")
        if lost:
            metrics.count("obs.events_dropped", len(lost))
            for t in lost:
                metrics.count(f"obs.events_dropped.{t}")
        return event

    def snapshot_delta(self) -> dict:
        """Record one ``telemetry_delta`` event: the registry COUNTER
        deltas since the last delta (or since construction). The dump
        audit replays these — base + Σdeltas must equal the final
        snapshot bit-exactly (tools/obs_report.py)."""
        snap = metrics.snapshot()
        with self._lock:
            base = self._base_snapshot
            self._base_snapshot = snap
        prev = base.get("counters", {})
        delta = {
            k: v - prev.get(k, 0)
            for k, v in snap.get("counters", {}).items()
            if v != prev.get(k, 0)
        }
        return self.record("telemetry_delta", counters=delta)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def drain(self) -> List[dict]:
        """Pop and return every buffered event (oldest first) — the
        idempotent JSONL-drain form: concurrent drains never hand the
        same event to two callers."""
        with self._lock:
            out, self._events[:] = list(self._events), []
        return out

    # ---- the postmortem artifact ----------------------------------------

    def dump(self, path: Optional[str] = None, *,
             reason: str = "manual") -> str:
        """Write the self-describing JSONL artifact: one
        ``flight_header`` line (format version, capacity, drop count,
        reason, and the registered event-type schemas), every buffered
        event (NOT drained — a dump is a read), and a final registry
        ``snapshot`` record for the bit-exact counter cross-check.
        Returns the path (default: ``flight-<reason>-<pid>-<n>.jsonl``
        under :func:`dump_dir`)."""
        from ..analysis.registry import obs_events

        if path is None:
            path = _next_dump_path(reason)
        snap = metrics.snapshot()
        with self._lock:
            events = list(self._events)
            header = {
                "record": "flight_header",
                "ts": self._clock(),
                "version": FORMAT_VERSION,
                "capacity": self.capacity,
                "events": len(events),
                "dropped": self.dropped,
                "dropped_by_type": dict(self.dropped_by_type),
                "reason": reason,
                "key": [self._generation, self._round, self._rank],
                "event_types": {
                    ev.name: {
                        "subsystem": ev.subsystem,
                        "fields": list(ev.fields),
                    }
                    for ev in obs_events()
                },
            }
        with open(path, "w") as f:
            for rec in [header] + events + [{
                "record": "snapshot", "ts": self._clock(),
                "counters": snap.get("counters", {}),
                "gauges": snap.get("gauges", {}),
            }]:
                # default=str: event fields may carry numpy/jnp scalars
                # — a postmortem dump must never crash the postmortem.
                f.write(json.dumps(rec, default=str) + "\n")
        metrics.count("obs.dumps")
        return path


# ---- the process-global recorder ------------------------------------------

_global_lock = threading.Lock()
_recorder: Optional[FlightRecorder] = None
_dump_dir: Optional[str] = None
_dump_counter = 0


def install(recorder: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Install (or with ``None`` remove) the process-global recorder
    every :func:`emit` site feeds. Returns the PREVIOUS recorder so
    tests can restore it."""
    global _recorder
    with _global_lock:
        prev, _recorder = _recorder, recorder
    return prev


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def current_key() -> Optional[Tuple[int, int, int]]:
    """The installed recorder's ``(generation, round, rank)`` key, or
    None — ``telemetry.span`` stamps this onto trace events."""
    rec = _recorder
    return rec.key() if rec is not None else None


def emit(etype: str, **fields) -> Optional[dict]:
    """Record one event on the installed recorder; a cheap no-op when
    none is installed (the default — instrumentation must cost nothing
    un-observed)."""
    rec = _recorder
    if rec is None:
        return None
    return rec.record(etype, **fields)


def advance_round(n: int = 1) -> None:
    """Advance the installed recorder's round counter (no-op
    uninstalled). Mesh drivers call this once per dispatch."""
    rec = _recorder
    if rec is not None:
        rec.advance_round(n)


def configure_auto_dump(directory: Optional[str]) -> None:
    """Point auto-dumps at ``directory`` (None = back to the
    ``CRDT_TPU_FLIGHT_DIR`` env var, then the system temp dir)."""
    global _dump_dir
    with _global_lock:
        _dump_dir = directory


def dump_dir() -> str:
    if _dump_dir:
        return _dump_dir
    env = os.environ.get("CRDT_TPU_FLIGHT_DIR")
    if env:
        return env
    import tempfile

    return tempfile.gettempdir()


def _next_dump_path(reason: str) -> str:
    global _dump_counter
    with _global_lock:
        _dump_counter += 1
        n = _dump_counter
    safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)
    return os.path.join(
        dump_dir(), f"flight-{safe}-{os.getpid()}-{n}.jsonl"
    )


def auto_dump(reason: str, **fields) -> Optional[str]:
    """The failure-boundary hook (``DrainRefused`` /
    ``DcnExchangeFailed`` / a non-empty ``StreamFaultReport`` /
    recovery): record one ``auto_dump`` event and write the artifact.
    No-op (returns None) when no recorder is installed — the hook
    sites stay unconditional and cost nothing un-observed. A dump
    failure is counted and swallowed: the postmortem path must never
    mask the exception that triggered it."""
    rec = _recorder
    if rec is None:
        return None
    try:
        rec.record("auto_dump", reason=reason, **fields)
        path = rec.dump(reason=reason)
        metrics.count("obs.auto_dumps")
        return path
    except OSError:
        metrics.count("obs.auto_dump_failed")
        return None


def recorder_conformant(recorder_cls) -> bool:
    """The ``obs`` static-check detector: a recorder class is
    conformant iff a ring of capacity C fed K > C events keeps exactly
    the LAST C in order and counts the K - C drops — in total AND per
    event type (the postmortem what-was-lost contract). The committed
    broken twin (``analysis.fixtures.recorder_drops_events``) silently
    discards events and must FAIL here — proving the detector fires."""
    cap, k = 8, 21
    try:
        rec = recorder_cls(capacity=cap)
        for i in range(k):
            rec.record("probe", seq=i)
        evs = rec.events()
    except Exception:
        return False
    if len(evs) != cap:
        return False
    if [e.get("seq") for e in evs] != list(range(k - cap, k)):
        return False
    if rec.dropped != k - cap:
        return False
    by_type = getattr(rec, "dropped_by_type", None)
    if by_type != {"probe": k - cap}:
        return False
    return True


# Recorder-owned event types; every other emitting subsystem registers
# its own next to the emit site (membership, retry, wal/snapshot/
# recover, stream, mesh_scale, elastic) — registration is the coverage
# contract the `obs` static-check section enforces.
def _register_events() -> None:
    from ..analysis.registry import register_obs_event

    register_obs_event(
        "telemetry", subsystem="telemetry",
        fields=("kind",), module=__name__,
    )
    register_obs_event(
        "telemetry_delta", subsystem="telemetry",
        fields=("counters",), module=__name__,
    )
    register_obs_event(
        "auto_dump", subsystem="obs", fields=("reason",), module=__name__,
    )
    register_obs_event(
        "probe", subsystem="obs", fields=("seq",), module=__name__,
    )


_register_events()


__all__ = [
    "DEFAULT_CAPACITY", "FORMAT_VERSION", "FlightRecorder",
    "advance_round", "auto_dump", "configure_auto_dump", "current_key",
    "dump_dir", "emit", "get_recorder", "install", "recorder_conformant",
]
