"""Sampled op-journey tracing + the per-tenant SLO plane (ISSUE 17
tentpole).

The serving pipeline (ingest → coalesced dispatch → WAL/persist →
δ fan-out push → client ack) measured its stages in isolation:
``hist_dispatch_us`` times only the device dispatch, and nothing
connected a submitted op to the moment a client replica could SEE it.
δ-sync exists precisely to keep thin clients fresh (Almeida et al.
1410.2803 / 1603.01529) — freshness is THE product metric — so this
module follows sampled ops end to end:

- :class:`Tracer` mints a trace id at ``IngestQueue.submit`` on a
  deterministic per-tenant sample (multiplicative-hash modulus — the
  same tenants sample on every run, so two runs are comparable). The
  trace rides the op through the pipeline, each boundary stamping
  ``(stage, t_ns)`` HOST-SIDE: the traced device program is untouched
  (the ``telemetry=``/``wal=`` host-side discipline), every hook is a
  no-op when no tracer is installed, and the sampling-off path is
  byte-identical to the pre-trace program (pinned by an HLO comparison
  test like the existing flag gates).
- **Chain stages** ``submit → coalesce → dispatch → durable → push →
  ack`` complete a trace on the first client ack covering its pushed
  version; **boundary stages** ``evict``/``restore`` mark the
  eviction-tier crossings the invariant audit reads but completion
  never waits on. A mid-flush :class:`CapacityOverflow` re-queue rolls
  an undispatched trace back to its submit stamp (the ingest queue's
  loss-free contract, mirrored: ops go back, traces go back).
- Completion derives the per-stage latencies (queue wait,
  coalesce→dispatch, dispatch→durable, dispatch→push, push→ack) plus
  the headline **end-to-end freshness** (submit→client-ack), folds
  them into host-side log2 histograms that ride the Telemetry pytree
  (:meth:`Tracer.annotate` — the per-record-increment fill discipline,
  so ``telemetry.combine`` folds runs exactly), and emits
  ``trace_stage``/``trace_complete`` flight-recorder events under the
  existing ``(generation, round, rank)`` correlation key —
  ``tools/obs_report.py --slo`` replays them bit-exactly against the
  recorded latencies (the counter cross-check discipline).
- :func:`skew_report` is the **hot-tenant skew attribution** view:
  top-K tenants by the evictor's touch counters, per-tenant ingest
  queue depth, and per-tenant freshness — exactly the load signal
  ROADMAP item 1's skew-aware rebalancing needs.

Stage names are REGISTERED
(``analysis.registry.register_trace_stage`` — all of them here, one
home) and every literal ``stamp("...")`` site under ``crdt_tpu/`` is
AST-scanned against the table by the ``slo`` static-check section: an
unregistered stage fails discovery, the ``register_obs_event`` rule
for the trace plane. :func:`tracer_conformant` is that section's
detector; the committed twins ``fixtures.tracer_skips_stage`` and
``fixtures.tracer_clock_regresses`` must FAIL it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..analysis.registry import register_obs_event, register_trace_stage
from ..utils.metrics import metrics
from . import hist as obs_hist
from . import recorder as _rec

# The submit→ack completion chain, in order; evict/restore are
# boundary markers (recorded on open traces, never gate completion).
CHAIN_STAGES = ("submit", "coalesce", "dispatch", "durable", "push", "ack")
BOUNDARY_STAGES = ("evict", "restore")

# (derived latency, from-stage, to-stage) — µs, integer floor of the
# ns stamp difference. ONE home for the derivation: the live tracer
# and the `obs_report --slo` replay both call derive_latencies, so the
# bit-exact cross-check cannot drift from the derivation.
LATENCIES = (
    ("queue_wait_us", "submit", "coalesce"),
    ("dispatch_gap_us", "coalesce", "dispatch"),
    ("durable_lag_us", "dispatch", "durable"),
    ("push_lag_us", "dispatch", "push"),
    ("ack_lag_us", "push", "ack"),
    ("freshness_us", "submit", "ack"),
)

# The Telemetry pytree fields the tracer fills (telemetry.py declares
# them; the schema, exporter exposition, and counter_increments pick
# them up generically off the hist_ prefix).
TRACE_HIST_FIELDS = tuple(f"hist_{name}" for name, _a, _b in LATENCIES)

_HASH = 0x9E3779B1  # Fibonacci hashing — spreads dense tenant ids
_EDGES_NP = np.asarray(obs_hist.EDGES, np.float64)


def sampled(tenant: int, sample: int) -> bool:
    """The deterministic per-tenant sampling decision: stable across
    runs and processes (no RNG), uniform over dense tenant-id ranges
    via multiplicative hashing. ``sample <= 1`` traces everyone."""
    if sample <= 1:
        return True
    return ((int(tenant) * _HASH) & 0xFFFFFFFF) % sample == 0


def sampled_mask(n_tenants: int, sample: int) -> np.ndarray:
    """Vectorized :func:`sampled` over the dense id range
    ``[0, n_tenants)`` — the bench legs use this to pre-register a
    fan-out subscriber per traced tenant so every sampled journey can
    complete (freshness is submit→client-ack)."""
    n = int(n_tenants)
    if sample <= 1:
        return np.ones(n, bool)
    ids = np.arange(n, dtype=np.uint64)
    return (
        ((ids * np.uint64(_HASH)) & np.uint64(0xFFFFFFFF))
        % np.uint64(sample) == 0
    )


def _host_bucket(v: float) -> int:
    """obs_hist.bucket_index replicated host-side (exact edge
    comparisons on the clamped value — bit-identical to the device
    fold, the histogram conformance contract)."""
    v = max(float(v), 0.0)
    return int((v > _EDGES_NP).sum())


def derive_latencies(stamps: Sequence) -> Dict[str, int]:
    """Stage latencies (integer µs) from one trace's stamp list
    (``[stage, t_ns]`` pairs; the FIRST occurrence of a chain stage
    wins — boundary stages and re-stamps never shift a derivation). A
    latency appears only when both of its stages were stamped."""
    first: Dict[str, int] = {}
    for stage, t in stamps:
        if stage not in first:
            first[stage] = int(t)
    out: Dict[str, int] = {}
    for name, a, b in LATENCIES:
        if a in first and b in first:
            out[name] = (first[b] - first[a]) // 1000
    return out


class _Trace:
    """One sampled op journey: the stamp list plus the pushed version
    the completing ack must cover. ``wal_seq`` is the durable record
    id the op's slab group-committed under (crdt_tpu/serve/wal.py) —
    set ONCE at the first ``durable`` stamp and preserved across
    requeues, so a WAL'd op that rolls back on CapacityOverflow
    re-dispatches under the SAME durable id its log record already
    carries (replay and trace ids agree after recovery)."""

    __slots__ = ("tid", "tenant", "stamps", "push_ver", "wal_seq")

    def __init__(self, tid: int, tenant: int):
        self.tid = tid
        self.tenant = tenant
        self.stamps: List[list] = []
        self.push_ver: Optional[int] = None
        self.wal_seq: Optional[int] = None

    def has(self, stage: str) -> bool:
        return any(s == stage for s, _t in self.stamps)


class Tracer:
    """The op-journey tracer (module docstring). ``sample`` is the
    per-tenant sampling modulus (1 = everyone); ``clock_ns`` is the
    injectable stamp clock (monotonic ns — tests and the SLO budget
    workload inject a deterministic ticker, and the clock-regression
    broken twin is exactly a tracer with a bad one); ``keep`` bounds
    the retained completed-trace records (:attr:`recent`)."""

    def __init__(
        self,
        *,
        sample: int = 64,
        clock_ns: Callable[[], int] = time.monotonic_ns,
        keep: int = 1024,
    ):
        self.sample = max(int(sample), 1)
        self.clock_ns = clock_ns
        self._lock = threading.Lock()
        self._open: Dict[int, List[_Trace]] = {}
        self._next_tid = 0
        self.minted = 0
        self.completed = 0
        self.requeued = 0
        self.recent: deque = deque(maxlen=max(int(keep), 1))
        # Drainable per-record histogram increments (the annotate fill
        # discipline) + the cumulative freshness distribution feeding
        # the live p99 gauge and per-tenant attribution.
        self._inc = {
            f: [np.zeros(obs_hist.NBUCKETS, np.uint64), 0.0]
            for f in TRACE_HIST_FIELDS
        }
        self._fresh_cum = np.zeros(obs_hist.NBUCKETS, np.uint64)
        self._fresh_total = 0.0
        self._tenant_fresh: Dict[int, list] = {}

    # ---- stamping --------------------------------------------------------
    def stamp(self, stage: str, *, tenant=None, tenants=None,
              version=None, count=None, seq=None, **_fields) -> None:
        """Record one pipeline boundary crossing. ``tenant``/
        ``tenants`` scope the stamp (None on ``durable`` = every
        dispatched trace — the WAL group-commit fsync covers the whole
        round); ``count`` caps traces stamped per tenant (the ingest
        flush takes at most ``depth`` ops per tenant, so only that
        many waiting traces coalesce); ``version`` is the fan-out
        plane's shipped (``push``) or promoted (``ack``) watermark
        version; ``seq`` (``durable`` only) is the serve-WAL record id
        the stamped ops group-committed under — recorded once per
        trace and sticky across requeues."""
        t_ns = int(self.clock_ns())
        with self._lock:
            if stage == "submit":
                self._submit(int(tenant), t_ns)
            elif stage in ("coalesce", "dispatch", "durable"):
                scope = tenants if tenants is not None else (
                    [tenant] if tenant is not None else None
                )
                self._chain(stage, scope, t_ns, count, seq)
            elif stage == "push":
                self._push(int(tenant), int(version), t_ns)
            elif stage == "ack":
                self._ack(int(tenant), int(version), t_ns)
            elif stage in BOUNDARY_STAGES:
                self._boundary(stage, int(tenant), t_ns)
            else:
                raise ValueError(f"unknown trace stage {stage!r}")

    def requeue(self, tenants, seq=None) -> int:
        """Roll coalesced-but-undispatched traces back to their submit
        stamp (the ingest queue's loss-free re-queue, mirrored: the
        op's next flush re-coalesces it). Returns traces rolled.

        ``seq`` is the durable WAL record id of the slab the op was
        rolled OUT of (the dirty-tenant WAL logs before dispatch, so a
        CapacityOverflow requeue can follow a successful group
        commit): the rolled trace RECORDS it — sticky, first seq wins
        — instead of losing it with the stamps, so the op's eventual
        re-dispatch completes under the id its durable record already
        carries and recovery replay agrees with the trace plane."""
        n = 0
        with self._lock:
            for ten in tenants:
                for tr in self._open.get(int(ten), ()):
                    if tr.has("dispatch") or not tr.has("coalesce"):
                        continue
                    tr.stamps[:] = tr.stamps[:1]
                    tr.push_ver = None
                    if seq is not None and tr.wal_seq is None:
                        tr.wal_seq = int(seq)
                    n += 1
                    self.requeued += 1
                    metrics.count("obs.trace.requeued")
                    _rec.emit(
                        "trace_requeue", trace=tr.tid, tenant=tr.tenant,
                        wal_seq=tr.wal_seq,
                    )
        return n

    # ---- stage internals (all under self._lock) --------------------------
    def _stamp_one(self, tr: _Trace, stage: str, t_ns: int) -> None:
        tr.stamps.append([stage, t_ns])
        metrics.count(f"obs.trace.stage.{stage}")
        _rec.emit(
            "trace_stage", stage=stage, trace=tr.tid, tenant=tr.tenant,
            t_ns=t_ns,
        )

    def _submit(self, tenant: int, t_ns: int) -> None:
        if not sampled(tenant, self.sample):
            return
        tr = _Trace(self._next_tid, tenant)
        self._next_tid += 1
        self.minted += 1
        self._open.setdefault(tenant, []).append(tr)
        metrics.count("obs.trace.minted")
        self._stamp_one(tr, "submit", t_ns)

    def _chain(self, stage: str, tenants, t_ns: int,
               count: Optional[int] = None, seq=None) -> None:
        prev = {"coalesce": "submit", "dispatch": "coalesce",
                "durable": "dispatch"}[stage]
        scope = (
            list(self._open) if tenants is None
            else [int(x) for x in tenants]
        )
        for ten in scope:
            left = len(self._open.get(ten, ())) if count is None else count
            for tr in self._open.get(ten, ()):
                if left <= 0:
                    break
                if tr.has(stage) or not tr.has(prev):
                    continue
                if (stage == "durable" and seq is not None
                        and tr.wal_seq is None):
                    tr.wal_seq = int(seq)
                self._stamp_one(tr, stage, t_ns)
                left -= 1

    def _push(self, tenant: int, version: int, t_ns: int) -> None:
        for tr in self._open.get(tenant, ()):
            if tr.has("push") or not tr.has("dispatch"):
                continue
            tr.push_ver = version
            self._stamp_one(tr, "push", t_ns)

    def _ack(self, tenant: int, version: int, t_ns: int) -> None:
        open_list = self._open.get(tenant)
        if not open_list:
            return
        done = [
            tr for tr in open_list
            if tr.push_ver is not None and tr.push_ver <= version
        ]
        for tr in done:
            self._stamp_one(tr, "ack", t_ns)
            open_list.remove(tr)
            self._complete(tr)
        if not open_list and done:
            del self._open[tenant]

    def _boundary(self, stage: str, tenant: int, t_ns: int) -> None:
        for tr in self._open.get(tenant, ()):
            self._stamp_one(tr, stage, t_ns)

    def _complete(self, tr: _Trace) -> None:
        lat = derive_latencies(tr.stamps)
        self.completed += 1
        metrics.count("obs.trace.completed")
        for name, v in lat.items():
            acc = self._inc[f"hist_{name}"]
            acc[0][_host_bucket(v)] += 1
            acc[1] += max(float(v), 0.0)
        f = lat.get("freshness_us")
        if f is not None:
            idx = _host_bucket(f)
            self._fresh_cum[idx] += 1
            self._fresh_total += max(float(f), 0.0)
            pt = self._tenant_fresh.setdefault(
                tr.tenant, [np.zeros(obs_hist.NBUCKETS, np.uint64), 0.0]
            )
            pt[0][idx] += 1
            pt[1] += max(float(f), 0.0)
            metrics.observe(
                "obs.trace.freshness_p99_us",
                obs_hist.quantile([int(c) for c in self._fresh_cum], 0.99),
            )
        rec = {
            "trace": tr.tid, "tenant": tr.tenant,
            "stamps": [list(s) for s in tr.stamps], "lat": dict(lat),
            "wal_seq": tr.wal_seq,
        }
        self.recent.append(rec)
        _rec.emit(
            "trace_complete", trace=tr.tid, tenant=tr.tenant,
            stamps=rec["stamps"], lat=rec["lat"], wal_seq=tr.wal_seq,
        )

    # ---- accounting ------------------------------------------------------
    @property
    def n_open(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._open.values())

    def open_traces(self) -> Dict[int, list]:
        """Snapshot of the in-flight traces (tests pin the composition
        invariants on this): ``{tenant: [(tid, stamps), ...]}``."""
        with self._lock:
            return {
                t: [(tr.tid, [list(s) for s in tr.stamps]) for tr in lst]
                for t, lst in self._open.items()
            }

    def freshness_dict(self) -> Dict[str, object]:
        """The cumulative end-to-end freshness distribution in the
        schema's ``histogram`` shape (obs_hist.summary renders
        p50/p95/p99 from it)."""
        with self._lock:
            return {
                "edges": list(obs_hist.EDGES),
                "counts": [int(c) for c in self._fresh_cum],
                "total": float(self._fresh_total),
            }

    def tenant_freshness(self, tenant: int) -> Optional[Dict[str, float]]:
        with self._lock:
            pt = self._tenant_fresh.get(int(tenant))
            if pt is None:
                return None
            d = {
                "edges": list(obs_hist.EDGES),
                "counts": [int(c) for c in pt[0]],
                "total": float(pt[1]),
            }
        return obs_hist.summary(d)

    # ---- the Telemetry fill (per-record increments) ----------------------
    def drain_hists(self) -> Dict[str, obs_hist.Hist]:
        """The per-stage latency Hist INCREMENTS since the last drain,
        as Telemetry subtrees — and reset, so every drained record
        carries exactly its own completions and ``telemetry.combine``
        folds records bit-exactly (the ingest ``annotate``
        discipline)."""
        import jax.numpy as jnp

        out = {}
        with self._lock:
            for field, (counts, total) in list(self._inc.items()):
                out[field] = obs_hist.Hist(
                    counts=jnp.asarray(counts.astype(np.uint32)),
                    total=jnp.float32(total),
                )
                self._inc[field] = [
                    np.zeros(obs_hist.NBUCKETS, np.uint64), 0.0,
                ]
        return out

    def annotate(self, tel):
        """Fill the trace-plane hist fields on a concrete Telemetry
        (no-op under tracing — host-owned fields only exist on
        concrete records)."""
        from .. import telemetry as tele

        if not tele.is_concrete(tel):
            return tel
        return tel._replace(**self.drain_hists())


# ---- the process-global tracer (the recorder install discipline) ----------

_install_lock = threading.Lock()
_tracer: Optional[Tracer] = None


def install_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install (or with ``None`` remove) the process-global tracer
    every hook site feeds. Returns the PREVIOUS tracer so tests and
    bench legs can restore it."""
    global _tracer
    with _install_lock:
        prev, _tracer = _tracer, tracer
    return prev


def get_tracer() -> Optional[Tracer]:
    return _tracer


def stamp(stage: str, **fields) -> None:
    """Stamp one pipeline boundary on the installed tracer; a cheap
    no-op when none is installed (the default — the hook sites stay
    unconditional and the untraced program is byte-identical)."""
    tr = _tracer
    if tr is None:
        return
    tr.stamp(stage, **fields)


def requeue(tenants, seq=None) -> int:
    """Module-level :meth:`Tracer.requeue` (no-op uninstalled) — the
    ingest flush's loss-free exception path calls this, passing the
    rolled slab's durable WAL seq when one was group-committed."""
    tr = _tracer
    if tr is None:
        return 0
    return tr.requeue(tenants, seq=seq)


# ---- hot-tenant skew attribution -------------------------------------------

def skew_report(*, evictor=None, queue=None, tracer: Optional[Tracer] = None,
                k: int = 8) -> Dict[str, object]:
    """Top-K hot-tenant attribution: tenants ranked by the evictor's
    lifetime touch counters (falling back to ingest queue depth when
    no evictor is attached), each row carrying its touches, recency,
    current queue depth, and — for sampled tenants — the per-tenant
    freshness summary. This is the ROADMAP item-1 load signal: a 10×
    hot-shard skew event shows up as touch concentration + a fat
    per-tenant freshness tail, attributable to named tenants."""
    tr = tracer if tracer is not None else _tracer
    tc = getattr(evictor, "touch_count", None) if evictor is not None else None
    rows: List[Dict[str, object]] = []
    if tc is not None:
        order = np.argsort(-np.asarray(tc), kind="stable")[: max(int(k), 0)]
        cand = [int(t) for t in order if tc[t] > 0]
    elif queue is not None:
        by_depth = sorted(
            queue.pending.items(), key=lambda kv: -len(kv[1])
        )[: max(int(k), 0)]
        cand = [int(t) for t, _q in by_depth]
    else:
        cand = []
    for t in cand:
        row: Dict[str, object] = {"tenant": t}
        if tc is not None:
            row["touches"] = int(tc[t])
            row["last_touch"] = int(evictor.last_touch[t])
        if queue is not None:
            row["queue_depth"] = len(queue.pending.get(t, ()))
        if tr is not None:
            fr = tr.tenant_freshness(t)
            if fr is not None:
                row["freshness_p50_us"] = fr["p50"]
                row["freshness_p99_us"] = fr["p99"]
                row["freshness_count"] = fr["count"]
        rows.append(row)
    return {
        "k": int(k),
        "by": "touches" if tc is not None else "queue_depth",
        "tenants": rows,
    }


# ---- the `slo` static-check detector ---------------------------------------

def tracer_conformant(tracer_cls) -> bool:
    """The ``slo`` static-check detector: drive a canonical two-tenant
    journey (submit → coalesce → requeue-one → re-coalesce → dispatch
    → durable → evict/restore → push → ack) under an injected
    deterministic clock and require: both traces complete (none
    orphaned, none double-completed), every chain stage stamped on
    each, stamp times monotonic non-decreasing in stamp order, the
    recorded latencies bit-equal to :func:`derive_latencies` of the
    stamps, non-negative freshness, and the requeue rolled exactly one
    trace back. The committed twins ``fixtures.tracer_skips_stage``
    (drops the durable stamp) and ``fixtures.tracer_clock_regresses``
    (a regressing stamp clock) must FAIL here — proving the detector
    has teeth."""
    ticks = [0]

    def clock():
        ticks[0] += 1000  # 1 µs per stamp — latencies count stamps
        return ticks[0]

    try:
        tr = tracer_cls(sample=1, clock_ns=clock)
        tr.stamp("submit", tenant=0)
        tr.stamp("submit", tenant=1)
        tr.stamp("coalesce", tenants=[0, 1])
        tr.requeue([1])
        tr.stamp("coalesce", tenants=[1])
        tr.stamp("dispatch", tenants=[0, 1])
        tr.stamp("durable")
        tr.stamp("evict", tenant=1)
        tr.stamp("restore", tenant=1)
        tr.stamp("push", tenant=0, version=1)
        tr.stamp("push", tenant=1, version=1)
        tr.stamp("ack", tenant=0, version=1)
        tr.stamp("ack", tenant=1, version=1)
        completed, n_open = tr.completed, tr.n_open
        minted, requeued = tr.minted, tr.requeued
        recent = list(tr.recent)
    except Exception:
        return False
    if (completed, n_open, minted, requeued) != (2, 0, 2, 1):
        return False
    seen = set()
    for rec in recent:
        if rec["trace"] in seen:
            return False
        seen.add(rec["trace"])
        stamps = rec["stamps"]
        times = [t for _s, t in stamps]
        if any(b < a for a, b in zip(times, times[1:])):
            return False
        if not set(CHAIN_STAGES) <= {s for s, _t in stamps}:
            return False
        if rec["lat"] != derive_latencies(stamps):
            return False
        if rec["lat"].get("freshness_us", -1) < 0:
            return False
    return len(seen) == 2


# ---- registrations (ONE home for all stage schemas) ------------------------

for _i, _s in enumerate(CHAIN_STAGES):
    register_trace_stage(_s, order=_i, chain=True, module=__name__)
for _i, _s in enumerate(BOUNDARY_STAGES):
    register_trace_stage(
        _s, order=len(CHAIN_STAGES) + _i, chain=False, module=__name__,
    )

register_obs_event(
    "trace_stage", subsystem="obs.trace",
    fields=("stage", "trace", "tenant", "t_ns"), module=__name__,
)
register_obs_event(
    "trace_complete", subsystem="obs.trace",
    fields=("trace", "tenant", "stamps", "lat", "wal_seq"),
    module=__name__,
)
register_obs_event(
    "trace_requeue", subsystem="obs.trace",
    fields=("trace", "tenant", "wal_seq"), module=__name__,
)


from ..analysis.registry import register_shared_field as _reg_sf  # noqa: E402

# Every Tracer field is touched under ``_lock`` (stamp/requeue run on
# whatever thread observed the op) — guard declaration means conflicts
# on these need no happens-before contract in analysis/concur.py.
for _f, _kind in (
    ("_open", "open per-op trace table"),
    ("_next_tid", "next trace id"),
    ("minted", "lifetime minted-trace counter"),
    ("completed", "lifetime completed-trace counter"),
    ("requeued", "lifetime requeued-trace counter"),
    ("recent", "completed-trace ring"),
    ("_inc", "per-window completed increment"),
    ("_fresh_cum", "cumulative freshness sum"),
    ("_fresh_total", "cumulative freshness count"),
    ("_tenant_fresh", "per-tenant freshness accumulators"),
):
    _reg_sf(_f, owner="Tracer", module=__name__, kind=_kind,
            guard="lock:_lock")

__all__ = [
    "BOUNDARY_STAGES", "CHAIN_STAGES", "LATENCIES", "TRACE_HIST_FIELDS",
    "Tracer", "derive_latencies", "get_tracer", "install_tracer",
    "requeue", "sampled", "sampled_mask", "skew_report", "stamp",
    "tracer_conformant",
]
