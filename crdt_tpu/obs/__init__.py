"""crdt_tpu.obs — the postmortem-grade observability plane.

Four layers on top of the PR 2 counters/gauges/spans:

- :mod:`crdt_tpu.obs.hist` — in-kernel log2 histograms (lax-only, so
  they ride the ``telemetry=`` Telemetry sidecar through jit and
  shard_map): per-round residue backlog, per-round post-mask payload
  bytes, per-round ack-window depth, and host-timed per-dispatch
  wall-clock, each summarized to p50/p95/p99 through the registry and
  the exporter.
- :mod:`crdt_tpu.obs.recorder` — the flight recorder: a bounded
  host-side ring of per-round structured events sharing one monotonic
  ``(generation, round, rank)`` correlation key with
  ``telemetry.span``, dumped as a self-describing JSONL artifact
  (auto-invoked on ``DrainRefused`` / ``DcnExchangeFailed`` /
  ``StreamFaultReport`` / recovery).
- :mod:`crdt_tpu.obs.trace` — sampled op-journey tracing + the
  per-tenant SLO plane: trace ids minted at ``IngestQueue.submit``
  ride coalescing, dispatch, WAL group-commit, evict/restore, fan-out
  push and promote-on-ack; completed journeys fold into per-stage
  latency histograms and the headline submit→client-ack freshness
  distribution (``Tracer.annotate`` fills the ``hist_*_us`` Telemetry
  fields; ``skew_report`` is the hot-tenant attribution view).
- ``tools/obs_report.py`` — renders a dump into an incident report
  (timeline, histogram summaries, invariant audit) and cross-checks
  its folded counters bit-exactly against the live registry.

:func:`static_checks` is the ``obs`` section of
``tools/run_static_checks.py`` — event-type registry coverage plus the
recorder/histogram conformance detectors and their broken twins.
"""

from __future__ import annotations

from typing import List

from . import hist
from .recorder import (
    FlightRecorder,
    advance_round,
    auto_dump,
    configure_auto_dump,
    current_key,
    dump_dir,
    emit,
    get_recorder,
    install,
    recorder_conformant,
)
from . import trace  # noqa: E402  (after recorder: trace stamps emit into it)
from .trace import (
    Tracer,
    get_tracer,
    install_tracer,
    skew_report,
    tracer_conformant,
)


def histogram_conformant(observe_fn) -> bool:
    """The ``obs`` static-check detector for the in-kernel histogram:
    jit-fold a fixed sample (zeros, sub-1 fractions, exact bucket
    boundaries, a top-bucket outlier) through ``observe_fn`` and
    compare counts bit-exactly to the host reference (one count per
    observation, each in the unique bucket its edge comparisons pick)
    plus total conservation. The committed broken twin
    (``analysis.fixtures.histogram_miscounts``) shifts boundary values
    one bucket down and must FAIL here."""
    import jax
    import numpy as np

    sample = [0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 1023.0, 1024.0,
              float(2 ** 20), float(2 ** 40), 7.0]

    def fold(values):
        h = hist.zeros()
        for v in values:
            h = observe_fn(h, v)
        return h

    try:
        out = jax.jit(fold)(tuple(sample))
        counts = np.asarray(out.counts)
        total = float(out.total)
    except Exception:
        return False
    want = np.zeros(hist.NBUCKETS, dtype=np.uint32)
    for v in sample:
        # Right-closed buckets: a boundary value counts under its own
        # inclusive `le` edge (the Prometheus contract — hist.py).
        idx = sum(v > e for e in hist.EDGES)
        want[idx] += 1
    if counts.shape != want.shape or not np.array_equal(counts, want):
        return False
    if int(counts.sum()) != len(sample):
        return False
    return total == float(np.float32(np.sum(np.float32(sample))))


def static_checks() -> List:
    """The ``obs`` static-check section (Finding list, empty = clean):

    1. **event-type coverage** — every literal event type at an
       ``emit("...")`` site anywhere under ``crdt_tpu/`` must have a
       registered schema (``analysis.registry.register_obs_event``);
       an event-emitting subsystem without one fails discovery, the
       same registration-is-the-coverage-contract rule as joins /
       entries / fault surfaces.
    2. **recorder conformance** — :class:`FlightRecorder` must keep
       the newest ``capacity`` events in order and count every drop;
       the broken twin (``analysis.fixtures.recorder_drops_events``)
       must FAIL the detector.
    3. **histogram conformance** — ``hist.observe`` folded under jit
       must match the host bucket reference bit-exactly; the broken
       twin (``fixtures.histogram_miscounts``) must FAIL it.
    """
    from ..analysis import fixtures
    from ..analysis.registry import unregistered_obs_events
    from ..analysis.report import Finding

    findings: List[Finding] = []

    for name, where in unregistered_obs_events():
        findings.append(Finding(
            "obs-event-coverage", name,
            f"event type emitted at {where} has no registered schema "
            "(register_obs_event) — the flight recorder cannot "
            "describe it in a dump header",
        ))

    if not recorder_conformant(FlightRecorder):
        findings.append(Finding(
            "obs-recorder-conformance", "FlightRecorder",
            "the flight recorder lost, reordered, or failed to count "
            "events (ring conformance probe)",
        ))
    if recorder_conformant(fixtures.recorder_drops_events):
        findings.append(Finding(
            "obs-recorder-conformance", "fixtures.recorder_drops_events",
            "the event-dropping broken twin PASSED the recorder "
            "conformance detector — the detector has no teeth",
        ))

    if not histogram_conformant(hist.observe):
        findings.append(Finding(
            "obs-histogram-conformance", "hist.observe",
            "the in-kernel histogram miscounts the fixed sample "
            "(bucket reference mismatch under jit)",
        ))
    if histogram_conformant(fixtures.histogram_miscounts):
        findings.append(Finding(
            "obs-histogram-conformance", "fixtures.histogram_miscounts",
            "the boundary-shifting broken twin PASSED the histogram "
            "conformance detector — the detector has no teeth",
        ))
    return findings


__all__ = [
    "FlightRecorder", "Tracer", "advance_round", "auto_dump",
    "configure_auto_dump", "current_key", "dump_dir", "emit",
    "get_recorder", "get_tracer", "hist", "histogram_conformant",
    "install", "install_tracer", "recorder_conformant", "skew_report",
    "static_checks", "trace", "tracer_conformant",
]
