"""Live mesh resizing: generation-stamped membership over a fixed axis.

Mesh width is frozen at trace time — the devices ARE the axis — so
"grow the ring" cannot mean growing the physical axis mid-program.
What CAN change live is the ring's *logical membership*: PR 8's
eviction already rebuilds the permutation over a subset of the axis
with the excluded ranks self-looping (``faults.inject.ring_perm`` — a
true bijection of the full axis, so every trace re-use and the PR 7
collective lint hold). This module generalizes that mechanism from
"failure exit" to "elastic membership": a PARKED rank (not yet
admitted, or gracefully drained) is ring-wise identical to an evicted
one — it self-loops, its (join-identity) rows contribute nothing, and
its top is excluded from the closure and the reclamation frontier.
Scale-out is then a pure membership transition:

- :meth:`ScaleoutMesh.admit` — pick parked ranks, **bootstrap** each
  newcomer by shipping ``decompose(live, ⊥-or-snapshot)`` divergence
  lanes (:mod:`.bootstrap` — the PR 9/10 rejoin path generalized to
  empty bases; a snapshot from the PR 10 tier is the warm-start base
  that ships only the log suffix), write the bootstrapped state into
  the newcomer's absolute row of the ``[P, ...]`` batch (the stream
  driver's absolute-block-index convention — rows are addressed by
  axis position, never by live offset), and rebuild the ring over the
  widened live set under a bumped **generation** stamp.
- :meth:`ScaleoutMesh.drain` — the graceful inverse of eviction: the
  operator stops routing ops to the rank, runs one flush ring over the
  current membership, and the rank leaves ONLY under a
  :class:`DrainCertificate` — ``residue == 0`` (the δ-ring convergence
  certificate: every mark walked all live devices) AND zero packets
  lost AND zero unacked out-lanes (no live peer lacks any of the
  drained rank's row content — checked by join-irreducible
  decomposition against every survivor, the ack-window's positive-
  knowledge test made end-of-life explicit). A partition, an
  under-budgeted flush, or an unflushed δ window REFUSES the
  certificate (:class:`DrainRefused`) and the rank stays live — drain
  never voids convergence certificates and never strands content.

Every membership transition re-traces the ring family for free: the
composed :class:`~crdt_tpu.faults.inject.FaultPlan` (whose ``evicted``
set carries the parked ranks) rides the jit-cache key, so generation g
and generation g+1 are different compiled programs over the same
physical axis. The **generation stamp** makes that explicit and
auditable: every rebuild yields a :class:`RingGeneration` validated by
``membership.validate_perm``, certificates and reports carry the
generation they were issued under, and a stale certificate (issued
under an older generation) is refused by :meth:`ScaleoutMesh.drain`.

Flags-off contract: a full-membership controller composes to NO fault
plan at all (``plan()`` returns ``base`` unchanged — ``None`` when no
base), so a mesh that never scales traces the byte-identical pre-flag
program, pinned the same way ``telemetry=`` / ``faults=`` are.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import obs
from ..faults.inject import FaultPlan, inv_ring_perm, ring_perm
from ..faults.membership import validate_perm
from ..utils.metrics import metrics

from .bootstrap import BootstrapReport, bootstrap


class RingGeneration(NamedTuple):
    """One generation-stamped ring rebuild: the live set and its
    (validated, bijective) up/down-ring permutations at generation
    ``gen``. Stamps certificates and reports so an operator can audit
    which mesh shape issued them."""

    gen: int
    live: Tuple[int, ...]
    perm: Tuple[Tuple[int, int], ...]
    inv_perm: Tuple[Tuple[int, int], ...]


class AdmitReport(NamedTuple):
    """One :meth:`ScaleoutMesh.admit` event's accounting."""

    ranks: Tuple[int, ...]            # ranks admitted this event
    generation: int                   # generation AFTER the rebuild
    bootstraps: Tuple[BootstrapReport, ...]
    bytes_shipped: float              # total bootstrap wire bytes


@dataclass(frozen=True)
class DrainCertificate:
    """The drain-complete certificate (ISSUE 11): what
    :func:`certify_drain` measured on the flush run. ``ok()`` is the
    gate :meth:`ScaleoutMesh.drain` enforces — residue 0 (the ring's
    own convergence certificate held), nothing lost on the wire, and
    no out-lane left unacked (every live survivor provably holds every
    row the drained rank holds)."""

    generation: int
    rank: int
    residue: int
    packets_lost: int
    lanes_unacked: int

    def ok(self) -> bool:
        return (
            self.residue == 0
            and self.packets_lost == 0
            and self.lanes_unacked == 0
        )


class DrainRefused(RuntimeError):
    """A drain whose certificate did not hold — the rank STAYS LIVE.
    Carries the refused certificate as ``.certificate``."""

    def __init__(self, cert: DrainCertificate, why: str):
        super().__init__(
            f"drain of rank {cert.rank} refused at generation "
            f"{cert.generation}: {why} ({cert})"
        )
        self.certificate = cert


def certify_drain(
    kind: str,
    rank: int,
    rows,
    residue,
    counters=None,
    *,
    generation: int = 0,
    live: Optional[Sequence[int]] = None,
) -> DrainCertificate:
    """Measure the drain-complete certificate for ``rank`` from one
    flush run's outputs: ``rows`` is the ring's returned ``[P, ...]``
    batch, ``residue`` its convergence indicator, ``counters`` the
    ``FaultCounters`` when the flush ran faulted (``None`` = reliable
    links, nothing lost by construction). ``lanes_unacked`` is the
    positive-knowledge check: the drained rank's row content
    decomposed over EVERY live survivor (``delta_opt.decompose`` —
    changed lanes are content some peer still lacks), maxed across
    survivors, plus a residual mismatch flag folded in (a diverged top
    or parked buffer is also unacked knowledge).

    ``live`` defaults to EVERY rank of the batch — sound only on a
    fully-live mesh. When any rank is parked, pass the live set
    (``ScaleoutMesh.drain`` does): a parked rank's join-identity row
    would otherwise read as a survivor that lacks everything and
    spuriously refuse the drain (refusal is the safe direction, but
    the certificate would be wrong about WHY). Always RETURNS the
    certificate — refusing is the caller's move (``DrainCertificate.ok``
    / :meth:`ScaleoutMesh.drain`), so tests and operators can inspect
    why a drain was refused."""
    from ..analysis.registry import get_decomposer
    from ..delta_opt.decompose import decompose

    residue = int(residue)
    lost = 0
    if counters is not None:
        lost = int(counters.packets_dropped) + int(counters.packets_rejected)
    p = jax.tree.leaves(rows)[0].shape[0]
    live = tuple(live) if live is not None else tuple(range(p))
    mine = jax.tree.map(lambda x: x[rank], rows)
    dec = get_decomposer(kind)
    unacked = 0
    for peer in live:
        if peer == rank:
            continue
        theirs = jax.tree.map(lambda x: x[peer], rows)
        d = decompose(kind, mine, theirs)
        lanes = int(jnp.sum(d.valid))
        # The peer's residual baseline: straight from the registered
        # split when there is one (a full second decomposition would
        # only be run to discard its lanes); the split-less override
        # path (broken-twin fixtures) falls back to self-decomposition.
        res_theirs = (
            dec.split(theirs)[1] if dec.split is not None
            else decompose(kind, theirs, theirs).residual
        )
        res_mismatch = int(any(
            not bool(jnp.array_equal(a, b))
            for a, b in zip(
                jax.tree.leaves(d.residual), jax.tree.leaves(res_theirs),
            )
        ))
        unacked = max(unacked, lanes + res_mismatch)
    return DrainCertificate(
        generation=generation, rank=rank, residue=residue,
        packets_lost=lost, lanes_unacked=unacked,
    )


def park_row(rows, rank: int):
    """Zero rank ``rank``'s row of a ``[P, ...]`` batch back to the
    join identity (the padding convention — ``mesh.pad_replicas`` seeds
    exactly these rows): the parked slot a future admit bootstraps
    into. Called AFTER a drain certificate — the content is already
    replicated on every survivor (that is what the certificate proves),
    so zeroing the drained rank's absolute row strands nothing."""
    return jax.tree.map(lambda x: x.at[rank].set(jnp.zeros_like(x[rank])), rows)


class ScaleoutMesh:
    """Host-side elastic-membership controller for one replica mesh
    axis of physical width ``n_ranks`` (the module docstring's
    contract). Tracks the live set, the generation counter, and the
    scale-out telemetry totals (:meth:`annotate`)."""

    def __init__(self, n_ranks: int, live: Optional[Sequence[int]] = None):
        if n_ranks < 1:
            raise ValueError("n_ranks must be >= 1")
        self.n_ranks = n_ranks
        live_set = set(range(n_ranks)) if live is None else set(live)
        if not live_set:
            raise ValueError("at least one rank must start live")
        for r in live_set:
            if not 0 <= r < n_ranks:
                raise ValueError(f"rank {r} outside [0, {n_ranks})")
        self._live = live_set
        self._generation = 0
        self.admits = 0
        self.drains = 0
        self.bootstrap_bytes = 0.0
        metrics.observe("scaleout.live_ranks", float(len(self._live)))

    # ---- state ------------------------------------------------------------

    @property
    def generation(self) -> int:
        return self._generation

    def live(self) -> Tuple[int, ...]:
        return tuple(sorted(self._live))

    @property
    def parked(self) -> Tuple[int, ...]:
        return tuple(
            r for r in range(self.n_ranks) if r not in self._live
        )

    def plan(self, base: Optional[FaultPlan] = None) -> Optional[FaultPlan]:
        """The fault plan the next ring run should compose under: the
        parked ranks ride the ``evicted`` set (newcomer self-loops —
        the evicted self-loop generalized), UNIONED with any ranks the
        base plan already evicts — a PR 8 membership eviction composed
        under scale-out must stay evicted, not silently re-enter the
        ring. FULL membership with no base returns ``None`` — the
        flags-off path must trace the byte-identical pre-flag program
        (module docstring)."""
        if base is None and not self.parked:
            return None
        base = base or FaultPlan()
        return base.with_evicted(set(self.parked) | set(base.evicted))

    def ring(self) -> RingGeneration:
        """The current generation-stamped ring rebuild, validated as a
        true bijection of the full axis at construction (a broken
        rebuild must fail HERE, not as a silent mis-wired collective).
        """
        perm = ring_perm(self.n_ranks, self.parked)
        errs = validate_perm(perm, self.n_ranks)
        if errs:
            raise ValueError(
                f"generation {self._generation} ring rebuild is not a "
                f"bijection: {'; '.join(errs)}"
            )
        return RingGeneration(
            gen=self._generation,
            live=self.live(),
            perm=tuple(perm),
            inv_perm=tuple(inv_ring_perm(self.n_ranks, self.parked)),
        )

    def _bump(self) -> None:
        self._generation += 1
        metrics.observe("scaleout.generation", float(self._generation))
        metrics.observe("scaleout.live_ranks", float(len(self._live)))
        # Every ring rebuild is a correlation-key transition: the
        # installed flight recorder (if any) adopts the new generation,
        # so spans and subsystem events after this line carry it.
        rec = obs.get_recorder()
        if rec is not None:
            rec.set_generation(self._generation)
        obs.emit("generation", generation=self._generation,
                 live=len(self._live))

    # ---- transitions ------------------------------------------------------

    def admit(
        self,
        k: int = 1,
        *,
        kind: Optional[str] = None,
        rows=None,
        base=None,
        faults: Optional[FaultPlan] = None,
        ranks: Optional[Sequence[int]] = None,
        segment_cap: int = 64,
        max_attempts: int = 64,
    ):
        """Admit ``k`` parked ranks (or the explicit ``ranks``) and
        re-trace the ring over the widened live set at generation+1.

        With ``rows`` (the current converged ``[P, ...]`` batch) and
        ``kind`` given, every newcomer is BOOTSTRAPPED first: the first
        live rank's row is the shipping peer, ``base`` the causal lower
        bound (``None`` = ⊥, the cold-start path; a PR 10 snapshot
        state = the warm start that ships only the log suffix), and
        ``faults`` an optional wire plan the bootstrap lanes cross
        (dropped/rejected segments re-ship — :func:`.bootstrap`). The
        bootstrapped state lands at the newcomer's ABSOLUTE row index.
        Without ``rows`` the transition is membership-only (the caller
        owns state placement). Returns ``(rows, AdmitReport)``."""
        if ranks is None:
            avail = self.parked
            if len(avail) < k:
                raise ValueError(
                    f"cannot admit {k}: only {len(avail)} parked ranks "
                    f"on a {self.n_ranks}-rank axis"
                )
            ranks = avail[:k]
        else:
            ranks = tuple(ranks)
            for r in ranks:
                if not 0 <= r < self.n_ranks:
                    raise ValueError(
                        f"rank {r} outside [0, {self.n_ranks})"
                    )
                if r in self._live:
                    raise ValueError(f"rank {r} is already live")
        reports: List[BootstrapReport] = []
        shipped = 0.0
        if rows is not None:
            if kind is None:
                raise ValueError("admit with rows= needs kind=")
            src = self.live()[0]
            peer = jax.tree.map(lambda x: x[src], rows)
            for r in ranks:
                state, rep = bootstrap(
                    kind, peer, base=base, faults=faults,
                    segment_cap=segment_cap, max_attempts=max_attempts,
                )
                rows = jax.tree.map(
                    lambda x, s: x.at[r].set(s.astype(x.dtype)), rows, state
                )
                reports.append(rep)
                shipped += rep.bytes_shipped
        self._live.update(ranks)
        self._bump()
        self.ring()  # validate the rebuilt permutation eagerly
        self.admits += len(ranks)
        self.bootstrap_bytes += shipped
        metrics.count("scaleout.admits", len(ranks))
        metrics.count("scaleout.bootstrap_bytes", int(shipped))
        obs.emit("scaleout_admit", ranks=list(ranks),
                 generation=self._generation,
                 bootstrap_bytes=float(shipped))
        return rows, AdmitReport(
            ranks=tuple(ranks), generation=self._generation,
            bootstraps=tuple(reports), bytes_shipped=shipped,
        )

    def drain(
        self,
        rank: int,
        *,
        certificate: Optional[DrainCertificate] = None,
        kind: Optional[str] = None,
        rows=None,
        residue=None,
        counters=None,
        certify=certify_drain,
    ) -> DrainCertificate:
        """Gracefully remove ``rank`` from the live set — ONLY under a
        holding drain-complete certificate. Pass either a pre-computed
        ``certificate`` (from :func:`certify_drain` on the flush run's
        outputs) or the flush outputs themselves (``kind`` + ``rows`` +
        ``residue`` [+ ``counters``]) and the certificate is measured
        here. Refusal (:class:`DrainRefused`) leaves membership AND
        generation untouched: the rank keeps serving, the operator
        re-flushes and retries. A certificate stamped by an older
        generation is stale and refused — membership changed since it
        was measured. On success the rank parks (self-loop, excluded
        from closure and frontier — reclamation unpinned exactly as
        eviction unpins it) and the generation bumps."""
        if rank not in self._live:
            raise ValueError(f"rank {rank} is not live")
        if len(self._live) <= 1:
            raise ValueError(
                f"draining rank {rank} would leave an empty mesh"
            )
        if certificate is None:
            if kind is None or rows is None or residue is None:
                raise ValueError(
                    "drain needs certificate= or (kind=, rows=, residue=)"
                )
            certificate = certify(
                kind, rank, rows, residue, counters,
                generation=self._generation, live=self.live(),
            )
        if certificate.rank != rank:
            raise ValueError(
                f"certificate is for rank {certificate.rank}, not {rank}"
            )
        if certificate.generation != self._generation:
            self._refuse(certificate, "stale certificate")
            raise DrainRefused(
                certificate,
                f"stale certificate: issued at generation "
                f"{certificate.generation}, mesh is at {self._generation}",
            )
        if not certificate.ok():
            why = []
            if certificate.residue:
                why.append(
                    f"residue {certificate.residue} > 0 — the flush ring "
                    f"is not certified converged"
                )
            if certificate.packets_lost:
                why.append(
                    f"{certificate.packets_lost} packets lost on the "
                    f"flush wire"
                )
            if certificate.lanes_unacked:
                why.append(
                    f"{certificate.lanes_unacked} out-lanes unacked — a "
                    f"survivor still lacks drained content"
                )
            self._refuse(certificate, "; ".join(why))
            raise DrainRefused(certificate, "; ".join(why))
        self._live.discard(rank)
        self._bump()
        self.ring()
        self.drains += 1
        metrics.count("scaleout.drains")
        obs.emit("scaleout_drain", rank=rank,
                 generation=self._generation,
                 residue=certificate.residue)
        return certificate

    @staticmethod
    def _refuse(certificate: DrainCertificate, why: str) -> None:
        """The drain-refusal postmortem boundary: record the refused
        certificate and auto-dump the flight artifact BEFORE the
        ``DrainRefused`` raise (obs/recorder.py — both no-ops when no
        recorder is installed, and a dump failure never masks the
        refusal itself)."""
        obs.emit(
            "drain_refused", rank=certificate.rank,
            generation=certificate.generation, why=why,
            residue=certificate.residue,
            packets_lost=certificate.packets_lost,
            lanes_unacked=certificate.lanes_unacked,
        )
        obs.auto_dump("drain_refused", rank=certificate.rank)

    # ---- telemetry --------------------------------------------------------

    def annotate(self, tel):
        """Fill the scale-out fields of a Telemetry pytree with this
        controller's running totals (host-side, the ``stream_*``/
        ``wal_*`` discipline — telemetry.py module docstring)."""
        return tel._replace(
            live_ranks=jnp.uint32(len(self._live)),
            scaleout_admits=jnp.uint32(self.admits),
            scaleout_drains=jnp.uint32(self.drains),
            bootstrap_bytes=jnp.float32(self.bootstrap_bytes),
        )


def drain_refuses_unflushed(certify_fn) -> bool:
    """Detector behind the ``scaleout`` static-check section: a sound
    certifier must REFUSE a drain whose rank still holds content some
    survivor lacks. Builds a 2-rank orswot batch where rank 1 holds one
    extra live row (an unacked out-lane by construction) and asks
    ``certify_fn`` for rank 1's certificate with a deceptive
    ``residue=0``: returns True iff the certificate does NOT hold. The
    committed broken twin (``analysis.fixtures.drain_ignores_unacked``)
    zeroes the unacked count and must FAIL here — proving the gate
    fires."""
    from ..analysis.registry import get_merge_kind

    states = get_merge_kind("orswot").states()
    base, ahead = states[0], states[-1]
    rows = jax.tree.map(
        lambda a, b: jnp.stack([a, b.astype(a.dtype)]), base, ahead
    )
    cert = certify_fn("orswot", 1, rows, 0, None, generation=0, live=(0, 1))
    return not cert.ok()


# ---- static-analysis registration (crdt_tpu.analysis) ---------------------
# Every public scaleout surface registers — the coverage contract the
# ``scaleout`` static-check section enforces (an unregistered public
# symbol fails run_static_checks discovery, the faults/entry-point rule).

from ..analysis.registry import register_scaleout_surface as _reg_so  # noqa: E402

_reg_so("ScaleoutMesh", module=__name__)
_reg_so("certify_drain", module=__name__)
_reg_so("park_row", module=__name__)
_reg_so("drain_refuses_unflushed", module=__name__)

from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev("generation", subsystem="scaleout",
        fields=("generation", "live"), module=__name__)
_reg_ev("scaleout_admit", subsystem="scaleout",
        fields=("ranks", "generation", "bootstrap_bytes"), module=__name__)
_reg_ev("scaleout_drain", subsystem="scaleout",
        fields=("rank", "generation", "residue"), module=__name__)
_reg_ev("drain_refused", subsystem="scaleout",
        fields=("rank", "generation", "why", "residue", "packets_lost",
                "lanes_unacked"),
        module=__name__)

__all__ = [
    "AdmitReport", "DrainCertificate", "DrainRefused", "RingGeneration",
    "ScaleoutMesh", "certify_drain", "drain_refuses_unflushed", "park_row",
]
