"""crdt_tpu.scaleout — elastic mesh scale-out (ISSUE 11).

PR 8 let the mesh shrink under failure (suspicion → eviction) and
PR 10 let a recovered rank come back; this package makes mesh shape an
OPERATOR DECISION under traffic: live rank join, graceful drain, and
policy-driven resizing. Three cooperating pieces (see each module's
docstring):

- :mod:`.mesh_scale` — the membership controller:
  :class:`ScaleoutMesh` tracks the live set over a fixed physical axis
  (parked ranks self-loop — ``inject.ring_perm``'s evicted self-loops
  generalized to newcomers), rebuilds the ring under a **generation
  stamp** on every transition (each generation is its own traced
  program — the composed FaultPlan rides the jit-cache key), and
  enforces the **drain-complete certificate**: ``residue == 0`` AND
  nothing lost AND no out-lane unacked, measured by join-irreducible
  decomposition against every survivor (:func:`certify_drain`). A
  refused drain leaves the rank live.
- :mod:`.bootstrap` — newcomer bootstrap: ship
  ``decompose(live, ⊥-or-snapshot)`` divergence lanes (the PR 9/10
  rejoin path generalized to empty bases; a PR 10 snapshot is the
  warm-start base that ships only the log suffix), segmented over an
  optionally faulted wire — dropped segments re-ship, checksum-rejected
  segments never join — landing the live state bit-exactly.
- :mod:`.autoscaler` — the policy half: fold ``widen_pressure``,
  ``frontier_lag``, streaming overlap misses, and DCN retries into one
  load signal and debounce it through ``elastic.Hysteresis.vote``
  (the symmetric widen/shrink governor) into admit/drain
  recommendations.

Plus :func:`static_checks` — the ``scaleout`` section of
tools/run_static_checks.py: surface-registry coverage, the
generation/bijection walk, and the broken-twin detector gates (the
corrupt-blind bootstrap and the unacked-blind drain certifier in
``analysis.fixtures`` must each be caught).

Flags-off contract: a full-membership ``ScaleoutMesh`` composes to NO
fault plan (``plan()`` → ``None``), so a mesh that never scales traces
byte-identical pre-flag programs — the ``telemetry=`` / ``faults=``
discipline, pinned in tests/test_scaleout.py.
"""

from __future__ import annotations

from typing import List

from .autoscaler import AutoscaleDecision, Autoscaler
from .bootstrap import (
    BootstrapFailed,
    BootstrapReport,
    bootstrap,
    bootstrap_rejects_corruption,
)
from .mesh_scale import (
    AdmitReport,
    DrainCertificate,
    DrainRefused,
    RingGeneration,
    ScaleoutMesh,
    certify_drain,
    drain_refuses_unflushed,
    park_row,
)


def static_checks() -> List:
    """The ``scaleout`` static-check section (Finding list, empty =
    clean):

    1. **surface coverage** — every public operational symbol of this
       package must have called
       ``analysis.registry.register_scaleout_surface``; an
       unregistered surface fails discovery (the same
       registration-is-the-coverage-contract rule as joins / entries /
       fault surfaces).
    2. **generation/bijection walk** — a canonical membership
       trajectory (partial start → admit ×2 → drain) must keep every
       rebuilt ring a true bijection of the full axis, strictly
       increase the generation at every transition, and compose to NO
       fault plan at full membership (the flags-off contract).
    3. **broken twins fire** — the corrupt-blind bootstrap twin
       (``analysis.fixtures.bootstrap_skips_checksum``) must FAIL
       :func:`bootstrap_rejects_corruption`, and the unacked-blind
       drain certifier twin (``fixtures.drain_ignores_unacked``) must
       FAIL :func:`drain_refuses_unflushed` — proving both detectors
       have teeth.
    """
    from ..analysis import fixtures
    from ..analysis.registry import unregistered_scaleout_surfaces
    from ..analysis.report import Finding
    from ..faults.membership import validate_perm

    findings: List[Finding] = []

    for name in unregistered_scaleout_surfaces():
        findings.append(Finding(
            "scaleout-surface-coverage", name,
            "public scaleout symbol never called "
            "register_scaleout_surface — the scaleout gate cannot see it",
        ))

    # 2. generation/bijection walk.
    sm = ScaleoutMesh(8, live=range(5))
    if sm.plan() is None:
        findings.append(Finding(
            "scaleout-generation", "ScaleoutMesh.plan",
            "partial membership must compose a fault plan (parked ranks "
            "must self-loop), got None",
        ))
    seen = [sm.generation]

    def check_ring():
        errs = validate_perm(list(sm.ring().perm), sm.n_ranks)
        for e in errs:
            findings.append(Finding(
                "scaleout-generation", f"generation {sm.generation}", e,
            ))

    try:
        check_ring()
        for _ in range(2):
            sm.admit(1)
            seen.append(sm.generation)
            check_ring()
        # Membership-only park (the drain transition minus the flush —
        # the certificate path itself is gated by the broken-twin
        # checks below and tests/test_scaleout.py).
        sm._live.discard(6)
        sm._bump()
        seen.append(sm.generation)
        check_ring()
    except Exception as exc:
        findings.append(Finding(
            "scaleout-generation", "membership-walk",
            f"canonical admit/drain walk crashed: "
            f"{type(exc).__name__}: {exc}",
        ))
    if seen != sorted(set(seen)):
        findings.append(Finding(
            "scaleout-generation", "generation-stamp",
            f"generations must strictly increase per transition, got "
            f"{seen}",
        ))
    full = ScaleoutMesh(4)
    if full.plan() is not None:
        findings.append(Finding(
            "scaleout-generation", "flags-off",
            "full membership must compose NO fault plan (the pre-flag "
            "byte-identity contract)",
        ))

    # 3. broken twins.
    if not bootstrap_rejects_corruption(bootstrap):
        findings.append(Finding(
            "bootstrap-integrity", "bootstrap",
            "the honest bootstrap failed to land bit-identical with "
            "rejections over a corrupt wire — lost or joined a bad lane",
        ))
    if bootstrap_rejects_corruption(fixtures.bootstrap_skips_checksum):
        findings.append(Finding(
            "broken-fixture-missed", "bootstrap_skips_checksum",
            "the corrupt-blind bootstrap twin PASSED the corruption "
            "detector — the bootstrap integrity gate is not actually "
            "firing",
        ))
    if not drain_refuses_unflushed(certify_drain):
        findings.append(Finding(
            "drain-certificate", "certify_drain",
            "the honest certifier issued a drain certificate while a "
            "survivor still lacked drained content",
        ))
    if drain_refuses_unflushed(fixtures.drain_ignores_unacked):
        findings.append(Finding(
            "broken-fixture-missed", "drain_ignores_unacked",
            "the unacked-blind drain certifier twin PASSED the refusal "
            "detector — the drain gate is not actually firing",
        ))
    return findings


from ..analysis.registry import register_scaleout_surface as _reg_so  # noqa: E402

_reg_so("static_checks", module=__name__)

__all__ = [
    "AdmitReport", "AutoscaleDecision", "Autoscaler", "BootstrapFailed",
    "BootstrapReport", "DrainCertificate", "DrainRefused",
    "RingGeneration", "ScaleoutMesh", "bootstrap",
    "bootstrap_rejects_corruption", "certify_drain",
    "drain_refuses_unflushed", "park_row", "static_checks",
]
