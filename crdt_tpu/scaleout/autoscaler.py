"""Policy-driven resizing: telemetry pressure → debounced admit/drain.

The mechanism half of scale-out (mesh_scale.py, bootstrap.py) is
deliberately operator-shaped — explicit admit/drain calls with
explicit certificates. This module is the policy half: an
:class:`Autoscaler` that watches the signals the mesh already emits —
``widen_pressure`` (parked-buffer occupancy, the in-jit headroom
inverse), ``frontier_lag`` (a straggler pinning reclamation),
streaming overlap misses (the double buffer losing its race — ingest
outrunning the mesh), and host-side DCN ``faults.retries`` — folds
them into ONE normalized load signal in [0, 1], and feeds it through
``elastic.Hysteresis.vote`` (the symmetric widen/shrink debouncer,
ISSUE 11's satellite): ``high_water``/``widen_rounds`` must hold
before an **admit** recommendation fires, ``low_water``/
``shrink_rounds`` before a **drain**, and a single spike or a single
quiet round decides nothing — the same no-thrash contract the shrink
governor has enforced since ISSUE 5.

Decisions are RECOMMENDATIONS (:class:`AutoscaleDecision`): the caller
executes ``ScaleoutMesh.admit``/``drain`` — the drain still goes
through its certificate, so a bad policy can waste a flush but can
never strand content or void a convergence certificate. The bench leg
(``bench.py --scaleout``) wires the loop end to end: spike → debounced
admit → sustained merges/s rises; quiet → debounced drain → certified
scale-in.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from ..elastic import DEFAULT_POLICY, ElasticPolicy, Hysteresis
from ..utils.metrics import metrics

from .mesh_scale import ScaleoutMesh


class AutoscaleDecision(NamedTuple):
    """One fired recommendation: ``action`` is ``"admit"`` or
    ``"drain"``, ``rank`` the suggested subject (the first parked rank
    for admits, the highest live rank for drains — the newest-admitted
    leaves first so a burst unwinds in LIFO order), ``pressure`` the
    folded signal that fired it, ``generation`` the membership it was
    computed against (stale decisions are visible, like stale drain
    certificates)."""

    action: str
    rank: int
    pressure: float
    generation: int


class Autoscaler:
    """Debounced admit/drain recommendations for one
    :class:`~crdt_tpu.scaleout.mesh_scale.ScaleoutMesh`.

    ``min_live``/``max_live`` clamp the recommendation range (a policy
    may never drain the mesh empty nor admit past the physical axis);
    ``lag_ref``/``retry_ref`` normalize the open-ended signals — a
    frontier lag of ``lag_ref`` clock steps (or ``retry_ref`` DCN
    retries per observation window) saturates that signal at 1.0."""

    def __init__(
        self,
        smesh: ScaleoutMesh,
        policy: ElasticPolicy = DEFAULT_POLICY,
        *,
        min_live: int = 1,
        max_live: Optional[int] = None,
        lag_ref: int = 16,
        retry_ref: int = 4,
    ):
        if min_live < 1:
            raise ValueError("min_live must be >= 1")
        self.smesh = smesh
        self.hysteresis = Hysteresis(policy)
        self.min_live = min_live
        self.max_live = (
            smesh.n_ranks if max_live is None
            else min(max_live, smesh.n_ranks)
        )
        self.lag_ref = max(lag_ref, 1)
        self.retry_ref = max(retry_ref, 1)

    def pressure(self, tel=None, *, retries: int = 0,
                 load: Optional[float] = None) -> float:
        """Fold one observation window's signals into [0, 1]: the max
        of parked-buffer ``widen_pressure``, normalized
        ``frontier_lag``, the streaming overlap-MISS fraction, the
        normalized DCN retry count, and an optional explicit ``load``
        (an ingest-side offered-load fraction the mesh cannot see from
        its own kernels — the bench leg's traffic spike). Max, not
        mean: ANY saturated subsystem is a reason to add capacity, and
        a mesh is only quiet when every signal is."""
        worst = 0.0 if load is None else min(max(float(load), 0.0), 1.0)
        if tel is not None:
            worst = max(worst, min(float(tel.widen_pressure), 1.0))
            worst = max(
                worst, min(int(tel.frontier_lag) / self.lag_ref, 1.0)
            )
            blocks = int(tel.stream_blocks)
            if blocks:
                miss = 1.0 - int(tel.stream_overlap_hit) / blocks
                worst = max(worst, min(max(miss, 0.0), 1.0))
        worst = max(worst, min(retries / self.retry_ref, 1.0))
        metrics.observe("scaleout.pressure", worst)
        return worst

    def observe(self, tel=None, *, retries: int = 0,
                load: Optional[float] = None,
                pressure: Optional[float] = None
                ) -> Optional[AutoscaleDecision]:
        """Record one observation window; return a fired (debounced)
        recommendation or ``None``. ``pressure=`` overrides the folded
        signal entirely (tests and replay drivers). A vote that cannot
        be acted on — nothing parked to admit, already at
        ``min_live``/``max_live`` — returns ``None`` rather than a
        decision the caller must refuse (its streak was still consumed:
        the plateau was observed, there is just no capacity move left)."""
        p = self.pressure(tel, retries=retries, load=load) \
            if pressure is None else pressure
        vote = self.hysteresis.vote("scaleout.pressure", p)
        live = self.smesh.live()
        if vote == "widen":
            parked = self.smesh.parked
            if parked and len(live) < self.max_live:
                metrics.count("scaleout.autoscale_admit_votes")
                return AutoscaleDecision(
                    action="admit", rank=parked[0], pressure=p,
                    generation=self.smesh.generation,
                )
        elif vote == "shrink":
            if len(live) > self.min_live:
                metrics.count("scaleout.autoscale_drain_votes")
                return AutoscaleDecision(
                    action="drain", rank=live[-1], pressure=p,
                    generation=self.smesh.generation,
                )
        return None


# ---- static-analysis registration (crdt_tpu.analysis) ---------------------

from ..analysis.registry import register_scaleout_surface as _reg_so  # noqa: E402

_reg_so("Autoscaler", module=__name__)

__all__ = ["AutoscaleDecision", "Autoscaler"]
