"""Newcomer bootstrap: the rejoin path generalized to empty bases.

Delta State Replicated Data Types (Almeida et al., PAPERS.md
1603.01529) make dynamic membership safe by construction — a newcomer
is just a replica whose causal lower bound is ⊥ — and the PR 9/10
machinery already ships exactly the right thing for a rank re-entering
with SOME lower bound: ``durability.recover.rejoin`` decomposes the
live state over the recovered one and ships only the divergence lanes.
This module is that path with the base generalized:

- **cold start** (``base=None``) — the lower bound is ⊥ (the join
  identity, all-zero planes: the ``mesh.pad_replicas`` padding
  convention). ``decompose(live, ⊥)`` emits every live row — a
  structured full-state ship, segmented and integrity-checked instead
  of one blind state copy.
- **warm start** (``base=`` a PR 10 snapshot state) — the newcomer (or
  a rejoining-as-new rank) restores the snapshot locally first, and
  the wire carries only ``decompose(live, snapshot)``: the log suffix.
  The ``bench.py --scaleout`` gate pins this at < 25% of full-state
  bytes.

The wire is REAL in the degraded sense: under a ``faults=``
:class:`~crdt_tpu.faults.inject.FaultPlan` every shipped segment
crosses the same drop/corrupt draws + checksum lane the streaming
fold's upload wire uses (``faults.block_wire``, keyed on the plan seed
and an absolute segment index so a chaos bootstrap replays
deterministically). A dropped segment never arrived — it re-ships. A
corrupt segment is REJECTED by the checksum verify and re-ships —
corrupted lanes never join (the broken twin
``analysis.fixtures.bootstrap_skips_checksum`` skips the verify and
must fail :func:`bootstrap_rejects_corruption`). Once every valid lane
and the residual have landed, ``reconstruct`` lands the live state
**bit-exactly** (the reconstruction law — positional diff is
unconditional, so even a non-lower-bound ``base`` reconstructs
exactly; it just stops being minimal).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..utils.metrics import metrics, state_nbytes


class BootstrapFailed(RuntimeError):
    """Segments still pending after ``max_attempts`` ship rounds — the
    wire is too lossy for the budget; raise the budget or heal the
    links first."""


class BootstrapReport(NamedTuple):
    """One newcomer bootstrap's accounting."""

    lanes: int                # valid δ lanes shipped (the divergence set)
    segments: int             # distinct wire segments (incl. the residual)
    reshipped: int            # segments that needed another attempt
    dropped: int              # segment ships lost on the wire
    rejected: int             # segment ships refused by the checksum lane
    bytes_shipped: float      # wire bytes including every re-ship
    bytes_payload: float      # the decomposition payload (bytes_useful form)
    bytes_full_state: float   # what a blind full-state ship would cost
    ratio: float              # payload / full — the headline quantity


def _seg_bytes(tree) -> float:
    return float(sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(tree)
    ))


def bootstrap(
    kind: str,
    live,
    base=None,
    *,
    faults=None,
    segment_cap: int = 64,
    max_attempts: int = 64,
    verify_checksums: bool = True,
) -> Tuple[object, BootstrapReport]:
    """Bootstrap one newcomer to ``live`` (a single un-batched state of
    registered merge ``kind``) by shipping ``decompose(live, base-or-⊥)``
    in ``segment_cap``-lane segments over an optionally faulted wire
    (module docstring). Returns ``(state, BootstrapReport)`` with
    ``state`` bit-identical to ``live``.

    ``verify_checksums=False`` is the broken-twin seam
    (``analysis.fixtures.bootstrap_skips_checksum``): production
    callers never pass it — a corrupt-blind receiver joins wire-flipped
    lanes and :func:`bootstrap_rejects_corruption` catches it."""
    from ..delta_opt.decompose import (
        decompose, decomposition_bytes, reconstruct,
    )
    from ..faults.inject import block_wire

    if segment_cap < 1:
        raise ValueError("segment_cap must be >= 1")
    ident = (
        base if base is not None
        else jax.tree.map(jnp.zeros_like, live)
    )
    d = decompose(kind, live, ident)
    n_lanes = int(d.valid.shape[-1])
    n_segs = max((n_lanes + segment_cap - 1) // segment_cap, 1)

    # Receive-side assembly buffers: lanes land positionally (absolute
    # lane indices — the stream driver's absolute-block-index
    # convention at δ granularity), the residual rides whole as its own
    # segment.
    lanes_rx = jax.tree.map(jnp.zeros_like, d.lanes)
    residual_rx = None

    # Pending queue: segment -1 is the residual (+ validity mask),
    # 0..n_segs-1 the lane slices.
    pending = [-1] + list(range(n_segs))
    dropped = rejected = reshipped = 0
    bytes_shipped = 0.0
    attempt = 0
    while pending:
        if attempt >= max_attempts:
            raise BootstrapFailed(
                f"{len(pending)} bootstrap segments still pending after "
                f"{max_attempts} attempts (dropped={dropped}, "
                f"rejected={rejected}) — raise max_attempts or heal the "
                f"links first"
            )
        still = []
        for seg in pending:
            if seg < 0:
                payload = (d.residual, d.valid)
            else:
                sl = slice(seg * segment_cap, (seg + 1) * segment_cap)
                payload = jax.tree.map(lambda x: x[sl], d.lanes)
            bytes_shipped += _seg_bytes(payload)
            if faults is not None:
                # Absolute wire index: (attempt, segment) — replayable
                # under the plan's seed like every other injected draw.
                bix = jnp.int32(attempt * (n_segs + 1) + (seg + 1))
                payload, code = block_wire(faults, bix, payload)
                code = int(code)
                if code == 1:
                    dropped += 1
                    reshipped += 1
                    still.append(seg)
                    continue
                if code == 2 and verify_checksums:
                    rejected += 1
                    reshipped += 1
                    still.append(seg)
                    continue
                # code == 0 — or the corrupt-blind twin seam joining a
                # rejected payload anyway (what the detector catches).
            if seg < 0:
                residual_rx = payload
            else:
                sl = slice(seg * segment_cap, (seg + 1) * segment_cap)
                lanes_rx = jax.tree.map(
                    lambda x, p: x.at[sl].set(p), lanes_rx, payload
                )
        pending = still
        attempt += 1

    res_rx, valid_rx = residual_rx
    got = reconstruct(
        kind, ident, type(d)(lanes=lanes_rx, valid=valid_rx, residual=res_rx)
    )
    payload_bytes = float(decomposition_bytes(d))
    full = float(state_nbytes(live))
    report = BootstrapReport(
        lanes=int(jnp.sum(d.valid)),
        segments=n_segs + 1,
        reshipped=reshipped,
        dropped=dropped,
        rejected=rejected,
        bytes_shipped=bytes_shipped,
        bytes_payload=payload_bytes,
        bytes_full_state=full,
        ratio=payload_bytes / full if full else 0.0,
    )
    metrics.count("scaleout.bootstrap_lanes", report.lanes)
    metrics.count("scaleout.bootstrap_reships", reshipped)
    return got, report


def bootstrap_rejects_corruption(bootstrap_fn) -> bool:
    """Detector behind the ``scaleout`` static-check section: run
    ``bootstrap_fn`` over a corrupt-heavy wire and return True iff the
    newcomer's state lands BIT-IDENTICAL to the live peer's AND at
    least one segment was checksum-rejected (the wire really fired).
    The honest :func:`bootstrap` passes — rejected segments re-ship
    until clean copies land; the committed corrupt-blind twin
    (``analysis.fixtures.bootstrap_skips_checksum``) joins a
    wire-flipped lane and must FAIL here, proving the integrity gate
    fires."""
    from ..analysis.registry import get_merge_kind
    from ..faults.inject import FaultPlan

    live = get_merge_kind("orswot").states()[-1]
    plan = FaultPlan(seed=23, corrupt=0.7)
    try:
        got, rep = bootstrap_fn(
            "orswot", live, faults=plan, segment_cap=2, max_attempts=256,
        )
    except BootstrapFailed:
        return False
    identical = all(
        bool(jnp.array_equal(a, b))
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(live))
    )
    return identical and rep.rejected > 0


# ---- static-analysis registration (crdt_tpu.analysis) ---------------------

from ..analysis.registry import register_scaleout_surface as _reg_so  # noqa: E402

_reg_so("bootstrap", module=__name__)
_reg_so("bootstrap_rejects_corruption", module=__name__)

__all__ = [
    "BootstrapFailed", "BootstrapReport", "bootstrap",
    "bootstrap_rejects_corruption",
]
