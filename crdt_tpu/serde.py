"""serde — wire/storage encoding for every CRDT state and op.

Reference: ``#[derive(Serialize, Deserialize)]`` on every type including
Ops (SURVEY.md §3 row 17) — the reference's whole transport story is
"serialize, caller ships bytes, apply/merge on arrival", and its
checkpoint story is the same bytes on disk (§6.4). This module is that
surface: ``encode``/``decode`` to a JSON-able tagged tree,
``to_bytes``/``from_bytes`` for the wire form.

Every encoding is canonical (sorted map/set iteration) so equal states
produce equal bytes. Payload values (actors, members, register values,
markers) may be None/bool/int/float/str/bytes and list/tuple/set/
frozenset/dict compositions — everything is tagged, so tuples, sets and
bytes round-trip exactly (plain JSON would flatten them).

``Map``'s ``val_default`` factory is serialized as a *prototype*: the
encoding of one empty child. Decoding rebuilds the factory as "decode
the prototype again", which round-trips any Val type — including nested
maps — without naming classes.
"""

from __future__ import annotations

import base64
import json
from typing import Any

from .dot import Dot, OrdDot
from .pure.gcounter import GCounter
from .pure.glist import GList
from .pure.glist import Insert as GInsert
from .pure.gset import GSet
from .pure.identifier import Identifier
from .pure.list import Delete, Insert, List
from .pure.lwwreg import LWWOp, LWWReg, UNSET
from .pure.map import Map, MapRm, Nop, Up
from .pure.merkle_reg import MerkleReg, Node
from .pure.mvreg import MVReg, Put
from .pure.orswot import Add, Orswot, Rm
from .pure.pncounter import Dir, PNCounter, PNOp
from .vclock import VClock


def _key(data) -> str:
    """Canonical sort key for encoded forms (order-stable across runs)."""
    return json.dumps(data, sort_keys=True, separators=(",", ":"))


def encode(obj: Any):
    """Encode a CRDT state / op / payload value to a JSON-able tree."""
    if obj is None:
        return ["n"]
    if isinstance(obj, bool):
        return ["?", obj]
    if isinstance(obj, int):
        return ["i", str(obj)]  # str: JSON numbers lose >2^53 precision
    if isinstance(obj, float):
        return ["f", repr(obj)]
    if isinstance(obj, str):
        return ["s", obj]
    if isinstance(obj, bytes):
        return ["b", base64.b64encode(obj).decode("ascii")]
    if isinstance(obj, tuple):
        return ["t", [encode(v) for v in obj]]
    if isinstance(obj, list):
        return ["l", [encode(v) for v in obj]]
    if isinstance(obj, (set, frozenset)):
        return ["e", sorted((encode(v) for v in obj), key=_key)]
    if isinstance(obj, dict) and type(obj) is dict:
        return [
            "d",
            sorted(([encode(k), encode(v)] for k, v in obj.items()), key=_key),
        ]

    if isinstance(obj, OrdDot):  # before Dot — distinct tag
        return ["OrdDot", encode(obj.actor), str(obj.counter)]
    if isinstance(obj, Dot):
        return ["Dot", encode(obj.actor), str(obj.counter)]
    if isinstance(obj, VClock):
        return [
            "VClock",
            sorted(
                ([encode(a), str(c)] for a, c in obj.dots.items()), key=_key
            ),
        ]
    if isinstance(obj, GCounter):
        return ["GCounter", encode(obj.inner)]
    if isinstance(obj, PNCounter):
        return ["PNCounter", encode(obj.p), encode(obj.n)]
    if isinstance(obj, PNOp):
        return ["PNOp", encode(obj.dot), obj.dir.value]
    if isinstance(obj, GSet):
        return ["GSet", sorted((encode(m) for m in obj.value), key=_key)]
    if isinstance(obj, LWWReg):
        if obj.val is UNSET:
            return ["LWWReg"]
        return ["LWWReg", encode(obj.val), encode(obj.marker)]
    if isinstance(obj, LWWOp):
        return ["LWWOp", encode(obj.val), encode(obj.marker)]
    if isinstance(obj, MVReg):
        return [
            "MVReg",
            sorted(
                (
                    [encode(d), encode(c), encode(v)]
                    for d, (c, v) in obj.vals.items()
                ),
                key=_key,
            ),
        ]
    if isinstance(obj, Put):
        return ["Put", encode(obj.dot), encode(obj.clock), encode(obj.val)]
    if isinstance(obj, Orswot):
        return [
            "Orswot",
            encode(obj.clock),
            sorted(
                ([encode(m), encode(c)] for m, c in obj.entries.items()),
                key=_key,
            ),
            sorted(
                (
                    [encode(c), sorted((encode(m) for m in ms), key=_key)]
                    for c, ms in obj.deferred.items()
                ),
                key=_key,
            ),
        ]
    if isinstance(obj, Add):
        return ["Add", encode(obj.dot), [encode(m) for m in obj.members]]
    if isinstance(obj, Rm):
        return ["Rm", encode(obj.clock), [encode(m) for m in obj.members]]
    if isinstance(obj, Map):
        return [
            "Map",
            encode(obj.val_default()),  # factory prototype (empty child)
            encode(obj.clock),
            sorted(
                ([encode(k), encode(v)] for k, v in obj.entries.items()),
                key=_key,
            ),
            sorted(
                (
                    [encode(c), sorted((encode(k) for k in ks), key=_key)]
                    for c, ks in obj.deferred.items()
                ),
                key=_key,
            ),
        ]
    if isinstance(obj, Up):
        return ["Up", encode(obj.dot), encode(obj.key), encode(obj.op)]
    if isinstance(obj, MapRm):
        return ["MapRm", encode(obj.clock), [encode(k) for k in obj.keyset]]
    if isinstance(obj, Nop):
        return ["Nop"]
    if isinstance(obj, Identifier):
        return [
            "Identifier",
            [[str(ix), encode(m)] for ix, m in obj.path],
        ]
    if isinstance(obj, List):
        return [
            "List",
            [[encode(i), encode(obj.vals[i])] for i in obj.seq],
            encode(obj.clock),
        ]
    if isinstance(obj, Insert):
        return ["Insert", encode(obj.id), encode(obj.val)]
    if isinstance(obj, Delete):
        return ["Delete", encode(obj.id), encode(obj.dot)]
    if isinstance(obj, GList):
        return ["GList", [encode(i) for i in obj.list]]
    if isinstance(obj, GInsert):
        return ["GInsert", encode(obj.id)]
    if isinstance(obj, Node):
        return [
            "Node",
            encode(obj.value),
            sorted(base64.b64encode(p).decode("ascii") for p in obj.parents),
        ]
    if isinstance(obj, MerkleReg):
        dag = sorted(obj.dag.values(), key=lambda n: n.hash())
        orphans = sorted(
            (n for waiting in obj.orphans.values() for n in waiting),
            key=lambda n: n.hash(),
        )
        return [
            "MerkleReg",
            [encode(n) for n in dag],
            [encode(n) for n in orphans],
        ]
    raise TypeError(f"crdt_tpu.serde cannot encode {type(obj).__name__}")


def decode(data) -> Any:
    """Inverse of ``encode``."""
    tag = data[0]
    if tag == "n":
        return None
    if tag == "?":
        return bool(data[1])
    if tag == "i":
        return int(data[1])
    if tag == "f":
        return float(data[1])
    if tag == "s":
        return data[1]
    if tag == "b":
        return base64.b64decode(data[1])
    if tag == "t":
        return tuple(decode(v) for v in data[1])
    if tag == "l":
        return [decode(v) for v in data[1]]
    if tag == "e":
        return frozenset(decode(v) for v in data[1])
    if tag == "d":
        return {decode(k): decode(v) for k, v in data[1]}

    if tag == "Dot":
        return Dot(decode(data[1]), int(data[2]))
    if tag == "OrdDot":
        return OrdDot(decode(data[1]), int(data[2]))
    if tag == "VClock":
        return VClock({decode(a): int(c) for a, c in data[1]})
    if tag == "GCounter":
        out = GCounter()
        out.inner = decode(data[1])
        return out
    if tag == "PNCounter":
        return PNCounter(decode(data[1]), decode(data[2]))
    if tag == "PNOp":
        return PNOp(dot=decode(data[1]), dir=Dir(data[2]))
    if tag == "GSet":
        return GSet(decode(m) for m in data[1])
    if tag == "LWWReg":
        if len(data) == 1:
            return LWWReg()
        return LWWReg(decode(data[1]), decode(data[2]))
    if tag == "LWWOp":
        return LWWOp(val=decode(data[1]), marker=decode(data[2]))
    if tag == "MVReg":
        return MVReg(
            {decode(d): (decode(c), decode(v)) for d, c, v in data[1]}
        )
    if tag == "Put":
        return Put(dot=decode(data[1]), clock=decode(data[2]), val=decode(data[3]))
    if tag == "Orswot":
        out = Orswot()
        out.clock = decode(data[1])
        out.entries = {decode(m): decode(c) for m, c in data[2]}
        out.deferred = {
            decode(c): {decode(m) for m in ms} for c, ms in data[3]
        }
        return out
    if tag == "Add":
        return Add(dot=decode(data[1]), members=tuple(decode(m) for m in data[2]))
    if tag == "Rm":
        return Rm(clock=decode(data[1]), members=tuple(decode(m) for m in data[2]))
    if tag == "Map":
        proto = data[1]
        out = Map(val_default=lambda: decode(proto))
        out.clock = decode(data[2])
        out.entries = {decode(k): decode(v) for k, v in data[3]}
        out.deferred = {
            decode(c): {decode(k) for k in ks} for c, ks in data[4]
        }
        return out
    if tag == "Up":
        return Up(dot=decode(data[1]), key=decode(data[2]), op=decode(data[3]))
    if tag == "MapRm":
        return MapRm(clock=decode(data[1]), keyset=tuple(decode(k) for k in data[2]))
    if tag == "Nop":
        return Nop()
    if tag == "Identifier":
        return Identifier(tuple((int(ix), decode(m)) for ix, m in data[1]))
    if tag == "List":
        out = List()
        for ident_data, val_data in data[1]:
            ident = decode(ident_data)
            out.seq.append(ident)
            out.vals[ident] = decode(val_data)
        out.clock = decode(data[2])
        return out
    if tag == "Insert":
        return Insert(id=decode(data[1]), val=decode(data[2]))
    if tag == "Delete":
        return Delete(id=decode(data[1]), dot=decode(data[2]))
    if tag == "GList":
        out = GList()
        out.list = [decode(i) for i in data[1]]
        return out
    if tag == "GInsert":
        return GInsert(id=decode(data[1]))
    if tag == "Node":
        return Node(
            value=decode(data[1]),
            parents=frozenset(base64.b64decode(p) for p in data[2]),
        )
    if tag == "MerkleReg":
        out = MerkleReg()
        for node_data in data[1]:
            out.apply(decode(node_data))
        for node_data in data[2]:
            out.apply(decode(node_data))
        return out
    raise ValueError(f"crdt_tpu.serde cannot decode tag {tag!r}")


def to_bytes(obj: Any) -> bytes:
    """The wire/storage form (canonical JSON, UTF-8)."""
    return json.dumps(encode(obj), sort_keys=True, separators=(",", ":")).encode(
        "utf-8"
    )


def from_bytes(raw: bytes) -> Any:
    return decode(json.loads(raw.decode("utf-8")))


__all__ = ["encode", "decode", "to_bytes", "from_bytes"]
