"""crdt_tpu.geo — the geo-federation plane (ISSUE 20, ROADMAP item 3).

One mesh is one failure domain; this package federates N of them into
a mesh of meshes (the SURVEY's inter-DC state/δ anti-entropy tier).
Four cooperating pieces (see each module's docstring):

- :mod:`.region` — :class:`RegionMap` (rendezvous tenant→region
  homing, minimal remap on region loss), :class:`FederationMembership`
  (generation-stamped, scaleout/mesh_scale.py discipline),
  :class:`RegionPlane` (one region's serve stack + local-interest
  signals) and :class:`Federation` (home-routed writes whose ack point
  stays the home region's ServeWal group commit). PARTIAL REPLICATION
  is the scale unlock: a region materializes only home ∪
  local-interest tenants (fan-out subscriptions + recent local
  writes), so tenant population × regions never multiplies device
  memory.
- :mod:`.antientropy` — per-link δ shipping: join-irreducible
  decomposition over the link's acked base (PR 9 ackwin semantics
  host-side — promote on positive ack, monotone watermarks), under
  retry + lockstep rounds + generation stamps + a checksum digest (a
  corrupt inter-region packet never joins).
- :mod:`.reads` — :class:`ReadCertificate` causal-watermark local
  reads: a mirror read is served locally WITH its explicit freshness
  bound; stale is labeled, never guessed fresh.
- :mod:`.failover` — region-kill re-homing from the durable tier plus
  peer divergence lanes: the FOURTH rejoin contract
  (faults/membership.py), zero acked-op loss.

Plus :func:`static_checks` — the ``federation`` section of
tools/run_static_checks.py: surface-registry coverage, the two-region
convergence/integrity micro A/B, and the broken-twin gate (the
always-fresh read path in ``analysis.fixtures`` must be caught by
:func:`reads.watermark_reads_sound`).
"""

from __future__ import annotations

from typing import List

from .antientropy import (
    ExchangeReport,
    GeoLink,
    GeoLockstepError,
    GeoPacket,
    apply_packet,
    build_packet,
    exchange,
    exchange_all,
    link_for,
)
from .failover import FailoverReport, fail_over_region
from .reads import ReadCertificate, read_local, watermark_reads_sound
from .region import (
    Federation,
    FederationMembership,
    GeoGenerationError,
    RegionMap,
    RegionPlane,
)


def static_checks() -> List:
    """The ``federation`` static-check section (Finding list, empty =
    clean):

    1. **surface coverage** — every public operational symbol of this
       package must have called
       ``analysis.registry.register_geo_surface`` (the
       registration-is-the-coverage-contract rule).
    2. **two-region convergence micro A/B** — disjoint home writes,
       one anti-entropy sweep: every mirror must land bit-identical
       to its home row, δ wire bytes must undercut the full-state
       mirroring baseline, and a corrupted packet must be REJECTED by
       the checksum lane (then healed by the retry re-ship) — never
       joined.
    3. **broken twin fires** — the always-fresh read path twin
       (``analysis.fixtures.region_serves_unwatermarked_read``) must
       FAIL :func:`reads.watermark_reads_sound`; the honest
       :func:`reads.read_local` must pass.
    """
    import jax
    import numpy as np

    from ..analysis import fixtures
    from ..analysis.registry import unregistered_geo_surfaces
    from ..analysis.report import Finding
    from .reads import _micro_federation

    findings: List[Finding] = []

    for name in unregistered_geo_surfaces():
        findings.append(Finding(
            "geo-surface-coverage", name,
            "public geo symbol never called register_geo_surface — "
            "the federation gate cannot see it",
        ))

    # 2. two-region convergence + δ economy + integrity rejection.
    try:
        fed = _micro_federation()
        t0 = next(
            t for t in range(fed.n_tenants) if fed.rmap.home(t) == 0
        )
        t1 = next(
            t for t in range(fed.n_tenants) if fed.rmap.home(t) == 1
        )
        m = lambda *on: np.isin(np.arange(4), on)  # noqa: E731
        # Written THROUGH the opposite region — both mirrors gain
        # local-write interest.
        fed.add(1, t0, actor=0, counter=1, member=m(0, 1))
        fed.add(0, t1, actor=1, counter=1, member=m(2))
        fed.drain_all()
        reps = exchange_all(fed)
        delta_b = sum(r.bytes_delta for r in reps)
        full_b = sum(r.bytes_full_mirror for r in reps)
        for tenant, home in ((t0, 0), (t1, 1)):
            mirror_region = 1 - home
            want = fed.plane(home).sb.row(tenant)
            got = fed.plane(mirror_region).sb.row(tenant)
            if not all(
                np.array_equal(a, b)
                for a, b in zip(jax.tree.leaves(got),
                                jax.tree.leaves(want))
            ):
                findings.append(Finding(
                    "geo-convergence", f"tenant {tenant}",
                    "mirror is not bit-identical to the home row "
                    "after one anti-entropy sweep",
                ))
        if not (0.0 < delta_b < full_b):
            findings.append(Finding(
                "geo-convergence", "delta-economy",
                f"δ wire bytes {delta_b} do not undercut the "
                f"full-state mirroring baseline {full_b}",
            ))
        # Integrity: flip one residual byte in flight — the checksum
        # lane must reject it (never joins) and the retry must heal
        # with the clean re-ship.
        fed.add(1, t0, actor=0, counter=2, member=m(3))
        fed.drain_all()
        calls = {"n": 0}

        def corrupt_once(pkt):
            calls["n"] += 1
            if calls["n"] > 1:
                return pkt
            d0 = pkt.deltas[0]
            bad = d0._replace(residual=jax.tree.map(
                lambda x: x + np.asarray(1, x.dtype).reshape(
                    (1,) * x.ndim
                ),
                d0.residual,
            ))
            return pkt._replace(deltas=(bad,) + pkt.deltas[1:])

        rep = exchange(fed, 0, 1, transport=corrupt_once)
        if rep.rejected < 1:
            findings.append(Finding(
                "geo-integrity", "checksum-lane",
                "a corrupted inter-region packet was not rejected by "
                "the checksum lane",
            ))
        want = fed.plane(0).sb.row(t0)
        got = fed.plane(1).sb.row(t0)
        if not all(
            np.array_equal(a, b)
            for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want))
        ):
            findings.append(Finding(
                "geo-integrity", f"tenant {t0}",
                "mirror diverged from home after the corrupt-packet "
                "retry heal",
            ))
    except Exception as exc:
        findings.append(Finding(
            "geo-convergence", "micro-federation",
            f"two-region micro A/B crashed: {type(exc).__name__}: "
            f"{exc}",
        ))

    # 3. watermark detector + broken twin, both directions.
    try:
        if not watermark_reads_sound(read_local):
            findings.append(Finding(
                "geo-watermark", "read_local",
                "the honest watermark-certified read path failed the "
                "freshness-labeling detector",
            ))
        if watermark_reads_sound(fixtures.region_serves_unwatermarked_read):
            findings.append(Finding(
                "broken-fixture-missed", "region_serves_unwatermarked_read",
                "the always-fresh read twin PASSED the watermark "
                "detector — the federation gate is not actually "
                "firing",
            ))
    except Exception as exc:
        findings.append(Finding(
            "geo-watermark", "detector",
            f"watermark detector crashed: {type(exc).__name__}: {exc}",
        ))
    return findings


from ..analysis.registry import register_geo_surface as _reg  # noqa: E402

for _name in (
    "RegionMap", "FederationMembership", "RegionPlane", "Federation",
    "GeoLink", "link_for", "build_packet", "apply_packet", "exchange",
    "exchange_all", "read_local", "watermark_reads_sound",
    "fail_over_region", "static_checks",
):
    _reg(_name, module=__name__)

__all__ = [
    "ExchangeReport", "FailoverReport", "Federation",
    "FederationMembership", "GeoGenerationError", "GeoLink",
    "GeoLockstepError", "GeoPacket", "ReadCertificate", "RegionMap",
    "RegionPlane", "apply_packet", "build_packet", "exchange",
    "exchange_all", "fail_over_region", "link_for", "read_local",
    "static_checks", "watermark_reads_sound",
]
