"""Region-kill failover — the fourth rejoin contract, inter-mesh.

``faults/membership.py`` pins three single-mesh re-entry paths
(full-state resync, log-suffix rejoin, bootstrap-from-⊥). Region loss
adds the FOURTH, inter-mesh form: a dead region's home shards re-home
to the surviving regions (minimal rendezvous remap —
:meth:`~crdt_tpu.geo.region.RegionMap.fail_over`), and each new home
rebuilds the tenant from

1. the dead region's DURABLE tier — snapshot rows
   (serve/evict.py ``recover_tenants``) plus the ServeWal suffix
   replayed through the new home's own ingest queue
   (the serve/wal.py discipline, filtered to the tenants this
   survivor inherited). Acks were gated on that WAL's group commit,
   so a complete durable tier recovers every acked op — the
   zero-acked-op-loss guarantee is the ack gate replayed, not a new
   mechanism;
2. PEER-REGION DIVERGENCE LANES — surviving mirrors, δ-decomposed
   against the recovered row. With a complete durable tier every
   mirror is a causal prefix of the recovery (divergence lanes count
   as telemetry only — adopting an older mirror over a fresher
   recovery would REGRESS acked state); a mirror is adopted wholesale
   only when the durable tier has NO trace of the tenant at all (the
   sole-survivor case).

After re-homing, every ack window touching a re-homed tenant resets
to ⊥ and every surviving mirror of it clears — δ re-entry from stale
acked bases is forbidden on this path exactly as on the other three
(the next exchange re-ships full state against a ⊥ mirror, keeping
positional reconstruction bit-exact). The federation generation bumps
(stale-stamped packets from before the failover are refused), and the
whole transition lands as one ``region_failover`` obs event.
"""

from __future__ import annotations

import os
from typing import Dict, List, NamedTuple, Optional, Set

import jax
import numpy as np

from ..delta_opt.decompose import decompose
from ..utils.metrics import metrics
from .antientropy import _materialized_row
from .region import Federation


class FailoverReport(NamedTuple):
    region: int            # the dead region
    generation: int        # federation generation after the bump
    tenants_rehomed: int
    rows_recovered: int    # snapshot rows landed at new homes
    ops_replayed: int      # WAL-suffix ops re-ingested
    divergence_lanes: int  # peer-mirror δ lanes vs the recovery
    mirrors_adopted: int   # sole-survivor mirrors adopted wholesale


def _replay_owned(queue, serve_wal, owned: Set[int], *,
                  since_seq: int = 0) -> int:
    """serve/wal.py ``replay_into`` filtered to one survivor's
    inherited tenants: same per-record drain (per-tenant submission
    order exact across slab boundaries), same AddOp/RmOp re-ingest —
    ops homed elsewhere are another survivor's to replay."""
    from ..ops import superblock as sb_ops
    from ..serve.ingest import AddOp, RmOp

    ops = 0
    for _seq, leaves in serve_wal.records(since_seq):
        tenants, kind_arr, actor, ctr, clock, member = leaves
        touched = False
        for k in range(len(tenants)):
            t = int(tenants[k])
            if t not in owned:
                continue
            for s in range(kind_arr.shape[1]):
                op_kind = int(kind_arr[k, s])
                if op_kind == sb_ops.NOOP:
                    continue
                if op_kind == sb_ops.ADD:
                    queue.submit(
                        t, AddOp(int(actor[k, s]), int(ctr[k, s]),
                                 np.asarray(member[k, s])),
                    )
                else:
                    queue.submit(
                        t, RmOp(np.asarray(clock[k, s], np.uint32),
                                np.asarray(member[k, s])),
                    )
                ops += 1
                touched = True
        if touched:
            queue.drain()
    return ops


def fail_over_region(
    fed: Federation, dead: int, *,
    snap_root: Optional[str] = None,
    serve_wal=None, wal_since: int = 0,
) -> FailoverReport:
    """Re-home a dead region's shards onto the survivors. The durable
    tier (``snap_root`` + ``serve_wal``, defaulting to the dead
    plane's own evictor root and WAL handle) must outlive the region —
    that is the deployment contract the ack gate already promised."""
    from .. import obs
    from ..serve.evict import recover_tenants

    dead = int(dead)
    dead_plane = fed.planes[dead]
    pre_home = {
        t: fed.rmap.home(t) for t in range(fed.n_tenants)
    }
    gen = fed.membership.evict(dead)   # refuses the last live region
    dead_plane.alive = False
    rehomed = [t for t, h in pre_home.items() if h == dead]

    snap_root = snap_root or (
        dead_plane.evictor.root if dead_plane.evictor is not None
        else None
    )
    serve_wal = serve_wal if serve_wal is not None else dead_plane.wal

    groups: Dict[int, List[int]] = {}
    for t in rehomed:
        groups.setdefault(fed.rmap.home(t), []).append(t)

    rows_recovered = 0
    ops_replayed = 0
    recovered_tenants: Set[int] = set()
    for new_home, tenants in sorted(groups.items()):
        plane = fed.plane(new_home)
        if snap_root is not None and os.path.isdir(snap_root):
            rows = recover_tenants(snap_root, plane.sb, tenants=tenants)
            for t, row in rows.items():
                plane.sb.write_row(int(t), row)
                plane.sb.dirty[int(t)] = False
                plane.sb.was_evicted[int(t)] = False
                recovered_tenants.add(int(t))
            rows_recovered += len(rows)
        if serve_wal is not None:
            replayed = _replay_owned(
                plane.queue, serve_wal, set(tenants),
                since_seq=wal_since,
            )
            if replayed:
                recovered_tenants.update(
                    t for t in tenants
                    if plane.sb.is_resident(int(t))
                )
            ops_replayed += replayed

    # Peer divergence lanes: surviving mirrors vs the recovery.
    divergence_lanes = 0
    mirrors_adopted = 0
    survivors = [r for r, p in fed.planes.items() if p.alive]
    for t in rehomed:
        new_home = fed.rmap.home(t)
        home_plane = fed.plane(new_home)
        for peer in survivors:
            if peer == new_home:
                continue
            old_link = fed.links.get((dead, peer))
            if old_link is None or old_link.watermark(t) <= 0:
                continue
            mirror = _materialized_row(fed.plane(peer), t)
            if t in recovered_tenants:
                base = _materialized_row(home_plane, t)
                d = decompose(fed.kind, mirror, base)
                divergence_lanes += int(np.asarray(d.valid).sum())
            else:
                # Sole survivor: the durable tier has no trace of the
                # tenant — the mirror IS the state of record now.
                home_plane.sb.write_row(
                    t, jax.tree.map(np.asarray, mirror)
                )
                recovered_tenants.add(t)
                mirrors_adopted += 1

    # ⊥ re-entry: drop the dead region's links outright, reset every
    # surviving ack window touching a re-homed tenant, and clear the
    # surviving mirrors so the next exchange re-ships full state
    # against ⊥ (δ re-entry from stale acked bases is forbidden).
    for key in [k for k in fed.links if dead in k]:
        del fed.links[key]
    for p in fed.planes.values():
        p.rounds_applied.pop(dead, None)
    rehomed_set = set(rehomed)
    for link in fed.links.values():
        link.reset(rehomed_set)
    for peer in survivors:
        plane = fed.plane(peer)
        for t in rehomed:
            if fed.rmap.home(t) != peer and plane.sb.is_resident(t):
                plane.sb.write_row(t, plane.sb.empty_row())

    fed.failovers += 1
    metrics.count("geo.failovers")
    rep = FailoverReport(
        region=dead, generation=gen, tenants_rehomed=len(rehomed),
        rows_recovered=rows_recovered, ops_replayed=ops_replayed,
        divergence_lanes=divergence_lanes,
        mirrors_adopted=mirrors_adopted,
    )
    obs.emit(
        "region_failover", region=dead, generation=gen,
        tenants=len(rehomed), recovered=rows_recovered,
        replayed=ops_replayed,
    )
    return rep


# ---- observability registration (crdt_tpu.analysis) -----------------------

from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev(
    "region_failover", subsystem="geo",
    fields=("region", "generation", "tenants", "recovered", "replayed"),
    module=__name__,
)
