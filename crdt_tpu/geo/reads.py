"""Causal-watermark local reads — freshness is LABELED, never guessed.

A non-home region answers reads from its local mirror instead of a
cross-region round trip; the price is staleness, and the contract is
that staleness is always EXPLICIT: every read returns a
:class:`ReadCertificate` stating the watermark the value reflects
(the home→here link's acked version — promoted only on positive ack,
so it is a floor the mirror provably reached), the home version it is
measured against, and the lag between them. ``fresh`` is the
certificate's verdict, not the server's optimism:

- home-region reads are fresh by definition (the home row IS the
  state of record for its applied prefix);
- a mirror read is fresh iff the link watermark has caught up to the
  home's applied version — anything less is served WITH its lag, and
  a consumer that needs fresh data escalates to the home region
  itself.

Watermarks are per-tenant MONOTONE (ack promotion never regresses —
delta_opt/ackwin.py semantics host-side), so successive certificates
for one tenant at one region never move backwards; the
:func:`watermark_reads_sound` detector pins both properties and the
``federation`` static-check section proves the committed broken twin
(``analysis.fixtures.region_serves_unwatermarked_read`` — a read path
that always claims fresh) fails it.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import numpy as np

from ..obs import hist as obs_hist
from ..utils.metrics import metrics
from .region import Federation


class ReadCertificate(NamedTuple):
    """The freshness bound attached to every region-local read."""

    tenant: int
    region: int        # where the read was served
    home: int          # the tenant's home region
    fresh: bool        # watermark has caught the home applied version
    watermark: int     # home version the served value provably reflects
    home_version: int  # home's applied version at certificate time
    lag: int           # home_version - watermark (0 when fresh)


def _applied_version(fed: Federation, tenant: int) -> int:
    """The home version the home ROW actually reflects: submitted ops
    minus the ones still queued (unflushed ops are not yet applied —
    and not yet acked, so the certificate must not count them)."""
    t = int(tenant)
    home = fed.rmap.home(t)
    queue = fed.plane(home).queue
    return int(fed.versions[t]) - len(queue.pending.get(t, ()))


def read_local(
    fed: Federation, region: int, tenant: int,
) -> Tuple[object, ReadCertificate]:
    """Serve ``tenant``'s observable value from ``region``'s own lanes
    with an explicit freshness certificate. Never blocks on another
    region; never claims fresh without the watermark to prove it."""
    plane = fed.plane(region)
    t = int(tenant)
    home = fed.rmap.home(t)
    hv = _applied_version(fed, t)

    if int(region) == home:
        wm = hv
    else:
        link = fed.links.get((home, int(region)))
        wm = link.watermark(t) if link is not None else 0
    lag = max(hv - wm, 0)
    cert = ReadCertificate(
        tenant=t, region=int(region), home=home,
        fresh=(lag == 0), watermark=int(wm), home_version=hv,
        lag=int(lag),
    )

    sb = plane.sb
    if not sb.is_resident(t):
        if plane.evictor is not None and sb.was_evicted[t]:
            plane.evictor.restore(t)
    if sb.is_resident(t):
        value = sb.read(t)
    else:
        value = jax.tree.map(
            np.asarray, sb.tk.observe(sb.empty_row())
        )
    fed.hist_watermark_lag = obs_hist.observe(
        fed.hist_watermark_lag, lag
    )
    metrics.observe("geo.read_lag", float(lag))
    if lag:
        metrics.count("geo.stale_reads")
    return value, cert


def _micro_federation(*, n_tenants: int = 8):
    """A two-region process-simulated federation on a 1×1 mesh —
    the detector/static-check workbench (no durable tier, no WAL:
    those live in the failover tests and the bench leg)."""
    from ..parallel import make_mesh
    from ..serve.ingest import IngestQueue
    from ..serve.superblock import Superblock
    from .region import Federation, RegionPlane

    mesh = make_mesh(1, 1)
    caps = dict(n_elems=4, n_actors=2, deferred_cap=2)
    planes = {}
    for r in (0, 1):
        sb = Superblock(n_tenants, mesh, kind="orswot", caps=caps)
        q = IngestQueue(sb, lanes=n_tenants, depth=2)
        planes[r] = RegionPlane(r, sb, q)
    return Federation(planes)


def watermark_reads_sound(read_fn) -> bool:
    """Detector behind the ``federation`` static-check section: drive
    ``read_fn(fed, region, tenant)`` through a write→read→exchange→
    read sequence on a two-region micro federation and require

    1. a mirror read BEFORE anti-entropy is labeled stale (``fresh``
       False, positive ``lag``) — never silently served as fresh;
    2. per-tenant watermarks are monotone across successive reads;
    3. after the exchange catches the link up, the read is labeled
       fresh AND the served value equals the home value bit-exactly.

    The honest :func:`read_local` passes; the committed twin
    (``analysis.fixtures.region_serves_unwatermarked_read``) claims
    fresh unconditionally and must FAIL here."""
    from .antientropy import exchange_all

    fed = _micro_federation()
    # A tenant homed at region 0, written THROUGH region 1 (so region
    # 1 holds local-write interest and will mirror it).
    tenant = next(
        t for t in range(fed.n_tenants) if fed.rmap.home(t) == 0
    )
    m = lambda *on: np.isin(np.arange(4), on)  # noqa: E731
    fed.add(1, tenant, actor=0, counter=1, member=m(0, 1))
    fed.drain_all()

    _, c0 = read_fn(fed, 1, tenant)
    if c0.fresh or c0.lag <= 0:
        return False  # stale mirror silently served as fresh
    exchange_all(fed)
    value, c1 = read_fn(fed, 1, tenant)
    if c1.watermark < c0.watermark:
        return False  # watermark regressed
    if not c1.fresh or c1.lag != 0:
        return False  # caught-up mirror mislabeled
    home_value, home_cert = read_fn(fed, 0, tenant)
    if not home_cert.fresh:
        return False
    return all(
        np.array_equal(a, b)
        for a, b in zip(
            jax.tree.leaves(value), jax.tree.leaves(home_value)
        )
    )
