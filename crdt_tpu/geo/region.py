"""Region planes + federation membership — the mesh-of-meshes layer.

One mesh is one failure domain (ROADMAP item 3): the serving tier
(crdt_tpu/serve/) and the fan-out plane (crdt_tpu/fanout/) both die
with the region hosting them. This module federates N such regions:

- :class:`RegionMap` — rendezvous-hashed tenant→**region** homing,
  the exact :class:`~crdt_tpu.serve.shard.TenantShardMap` discipline
  layered one level up (a distinct splitmix64 salt decorrelates the
  region layer from the per-host layer, so a region's tenant set
  spreads evenly over its hosts). Every tenant has ONE home region —
  the single writer; rendezvous makes region loss a minimal remap.
- :class:`FederationMembership` — generation-stamped membership
  reusing the ``scaleout/mesh_scale.py`` discipline: every
  evict/admit bumps the generation, and every cross-region packet
  carries the generation it was built under. A packet stamped with a
  stale generation is REFUSED loudly (:class:`GeoGenerationError`),
  exactly like a stale :class:`~crdt_tpu.scaleout.mesh_scale
  .DrainCertificate` — the split-brain guard for the federation.
- :class:`RegionPlane` — one region's full serving stack (superblock
  + evictor + WAL-attached ingest queue + optional fan-out plane)
  plus the region-local interest signals that drive PARTIAL
  replication: a region materializes a non-home tenant only when it
  has local subscribers (the fan-out plane's interest table) or
  recent local writes (``local_writes``, stamped at the federation
  front door). Global tenant population × regions must NOT multiply
  device memory — the resident lane count per region is bounded by
  home ∪ local-interest, which ``bench.py --geo`` measures rather
  than asserts.
- :class:`Federation` — the front door: writes route to the tenant's
  HOME region's ingest queue (the ack point stays the home region's
  :class:`~crdt_tpu.serve.wal.ServeWal` group commit — ``flush`` on
  the home queue, nothing geo-specific), stamping origin-region
  interest so anti-entropy knows which mirrors to feed.

All regions must share one tenant kind and one capacity layout:
cross-region δ lanes are positional (delta_opt/decompose.py), so a
capacity divergence between regions would make reconstruction
meaningless. The constructor enforces it; capacity autoscale under
federation must be coordinated federation-wide (future work — the
exchange fails loudly on drift rather than joining garbage).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set

import jax
import numpy as np

from ..obs import hist as obs_hist
from ..utils.metrics import metrics
from .. import telemetry as tele


def _region_weight(tenant: int, region: int) -> int:
    """Deterministic (tenant, region) rendezvous weight — the
    serve/shard.py splitmix64 round under a geo-distinct increment, so
    region homing does not correlate with per-host shard placement."""
    z = (
        (tenant & 0xFFFFFFFF) << 32 | (region & 0xFFFFFFFF)
    ) + 0xD1B54A32D192ED03
    z &= 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


class GeoGenerationError(RuntimeError):
    """A cross-region operation carried a stale federation generation —
    membership changed under it. Refused loudly (the
    scaleout/mesh_scale.py stale-certificate discipline at federation
    granularity); the caller must re-read membership and rebuild."""


class RegionMap:
    """Rendezvous-hashed tenant→region homing over a live region set."""

    def __init__(self, n_regions: int,
                 live: Optional[Iterable[int]] = None):
        if n_regions < 1:
            raise ValueError("need at least one region")
        self.n_regions = n_regions
        self.live = set(range(n_regions) if live is None else live)
        if not self.live <= set(range(n_regions)):
            raise ValueError(
                f"live regions {self.live} exceed {n_regions}"
            )
        if not self.live:
            raise ValueError("no live regions")
        # Placement overrides (tenant → region), consulted BEFORE the
        # rendezvous hash — the serve/shard.py override discipline, so
        # a future geo rebalancer can pin hot tenants without moving
        # anything else.
        self.overrides: Dict[int, int] = {}

    def home(self, tenant: int) -> int:
        o = self.overrides.get(int(tenant))
        if o is not None and o in self.live:
            return o
        return max(self.live, key=lambda r: _region_weight(tenant, r))

    def homed(self, region: int, tenants: Sequence[int]) -> List[int]:
        return [t for t in tenants if self.home(t) == region]

    def fail_over(self, region: int) -> None:
        """Membership evicted a region: its tenants re-home to
        survivors by rendezvous, every other assignment untouched
        (minimal remap). Overrides pointing at the dead region are
        dropped — those tenants fall back to rendezvous too."""
        if region not in self.live:
            return
        if len(self.live) == 1:
            raise ValueError("cannot fail over the last live region")
        self.live.discard(region)
        for t in [t for t, r in self.overrides.items() if r == region]:
            del self.overrides[t]
        metrics.count("geo.region.failovers")

    def admit(self, region: int) -> None:
        if not 0 <= region < self.n_regions:
            raise ValueError(f"region {region} out of range")
        self.live.add(region)


class FederationMembership:
    """Generation-stamped federation membership (mesh_scale
    discipline): every evict/admit bumps ``generation``; cross-region
    packets stamp the generation they were built under and are refused
    on mismatch."""

    def __init__(self, rmap: RegionMap):
        self.rmap = rmap
        self.generation = 1

    def evict(self, region: int) -> int:
        self.rmap.fail_over(region)
        self.generation += 1
        return self.generation

    def admit(self, region: int) -> int:
        self.rmap.admit(region)
        self.generation += 1
        return self.generation

    def require(self, generation: int, *, op: str = "exchange") -> None:
        if generation != self.generation:
            raise GeoGenerationError(
                f"geo {op} stamped generation {generation} but the "
                f"federation is at {self.generation} — membership "
                f"changed; rebuild against current membership"
            )


class RegionPlane:
    """One region's serving stack plus its local-interest signals.

    ``superblock``/``evictor``/``queue`` are the PR 15/18 tier exactly
    as a single mesh runs them — the queue's attached
    :class:`~crdt_tpu.serve.wal.ServeWal` stays THE ack point for
    writes homed here. ``fanout`` (optional) contributes the
    subscriber half of the interest table; ``local_writes`` is the
    recent-local-writer half, stamped by
    :meth:`Federation.submit` for the ORIGIN region of every op so
    anti-entropy mirrors tenants written through this region even when
    nobody here subscribes."""

    def __init__(self, region: int, superblock, queue, *,
                 evictor=None, wal=None, fanout=None):
        self.region = int(region)
        self.sb = superblock
        self.queue = queue
        self.evictor = evictor
        self.wal = wal
        self.fanout = fanout
        self.alive = True
        self.local_writes = np.zeros(superblock.n_tenants, bool)
        # Receiver-side lockstep state: last anti-entropy round applied
        # per source region (geo/antientropy.py bumps these).
        self.rounds_applied: Dict[int, int] = {}

    def interest_tenants(self) -> Set[int]:
        """Tenants this region must materialize beyond its home set:
        local subscribers (fan-out interest table) ∪ recent local
        writers. This set — not the global tenant population — bounds
        the region's mirror lanes (the partial-replication
        contract)."""
        out: Set[int] = set(
            int(t) for t in np.nonzero(self.local_writes)[0]
        )
        if self.fanout is not None:
            st = self.fanout.sub_tenant[: self.fanout._top]
            out.update(int(t) for t in st[st >= 0])
        return out

    def resident_lanes(self) -> int:
        return int(self.sb.n_resident)


class Federation:
    """The multi-region front door: home-routed writes, shared
    membership, per-tenant home-version counters (the causal
    watermark's numerator — geo/reads.py compares a link's acked
    version against these)."""

    def __init__(self, planes: Dict[int, RegionPlane],
                 rmap: Optional[RegionMap] = None):
        if not planes:
            raise ValueError("a federation needs at least one region")
        self.planes = dict(planes)
        n = max(self.planes) + 1
        self.rmap = rmap or RegionMap(n, live=self.planes.keys())
        self.membership = FederationMembership(self.rmap)
        kinds = {p.sb.kind for p in self.planes.values()}
        capss = {tuple(sorted(p.sb.caps.items()))
                 for p in self.planes.values()}
        if len(kinds) != 1 or len(capss) != 1:
            raise ValueError(
                "federated regions must share one tenant kind and one "
                f"capacity layout (got kinds={kinds})"
            )
        self.kind = kinds.pop()
        self.n_tenants = next(iter(self.planes.values())).sb.n_tenants
        # Per-tenant home version: bumped once per op accepted at the
        # tenant's home region. Monotone by single-writer homing; the
        # read-path watermark certificates are lags against this.
        self.versions = np.zeros(self.n_tenants, np.int64)
        # Anti-entropy links keyed (src, dst) — geo/antientropy.py
        # owns their state; registered here so failover can reset
        # every link touching a re-homed tenant.
        self.links: Dict[tuple, object] = {}
        self.exchanges = 0
        self.exchange_bytes = 0.0
        self.full_mirror_bytes = 0.0
        self.failovers = 0
        self.hist_watermark_lag = obs_hist.zeros()

    # ---- routing --------------------------------------------------------
    def plane(self, region: int) -> RegionPlane:
        p = self.planes.get(int(region))
        if p is None or not p.alive:
            raise KeyError(f"region {region} is not live")
        return p

    def submit(self, origin: int, tenant: int, op) -> int:
        """Route one op (serve.ingest ``AddOp``/``RmOp``) to the
        tenant's HOME region's queue and stamp origin-region interest.
        Returns the home region id. The op is NOT acked here — acks
        stay gated on the home region's ServeWal group commit, i.e.
        the home queue's flush/drain."""
        home = self.rmap.home(tenant)
        self.plane(home).queue.submit(int(tenant), op)
        self.versions[int(tenant)] += 1
        origin_plane = self.planes.get(int(origin))
        if origin_plane is not None and origin_plane.alive:
            origin_plane.local_writes[int(tenant)] = True
        return home

    def add(self, origin: int, tenant: int, actor: int, counter: int,
            member) -> int:
        from ..serve.ingest import AddOp

        return self.submit(
            origin, tenant, AddOp(actor, counter, np.asarray(member))
        )

    def rm(self, origin: int, tenant: int, clock, member) -> int:
        from ..serve.ingest import RmOp

        return self.submit(
            origin, tenant,
            RmOp(np.asarray(clock, np.uint32), np.asarray(member)),
        )

    def drain_all(self) -> int:
        """Drain every live region's queue (each drain is that
        region's own WAL-gated flush loop). Returns ops applied."""
        ops = 0
        for p in self.planes.values():
            if not p.alive:
                continue
            rep, _ = p.queue.drain()
            ops += rep.ops_applied
        return ops

    # ---- telemetry ------------------------------------------------------
    def annotate(self, tel: "tele.Telemetry") -> "tele.Telemetry":
        """Fill the host-owned federation gauges/counters on a
        concrete Telemetry (the ``stream_*``/``wal_*`` fill
        discipline)."""
        if not tele.is_concrete(tel):
            return tel
        live = sum(1 for p in self.planes.values() if p.alive)
        home = sum(
            len(self.rmap.homed(r, range(self.n_tenants)))
            for r, p in self.planes.items() if p.alive
        )
        return tel._replace(
            regions_live=np.uint32(live),
            geo_home_tenants=np.uint32(home),
            geo_exchanges=np.uint32(self.exchanges),
            geo_exchange_bytes=np.float32(self.exchange_bytes),
            geo_full_mirror_bytes=np.float32(self.full_mirror_bytes),
            geo_failovers=np.uint32(self.failovers),
            hist_geo_watermark_lag=jax.tree.map(
                np.asarray, self.hist_watermark_lag
            ),
        )
