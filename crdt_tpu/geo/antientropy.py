"""Cross-region anti-entropy — δ lanes over retry-wrapped DCN links.

The inter-region cadence is the SURVEY's state/δ-based anti-entropy
between data centers: slower than the intra-mesh δ ring, affordable
because a link ships only the join-irreducible decomposition of what
the peer provably lacks (delta_opt/decompose.py, Enes et al.). One
:class:`GeoLink` per directed (home → mirror) region pair carries the
PR 9 ``ackwin`` semantics re-instantiated host-side:

- the sender keeps its own **shipped copy** per tenant and promotes it
  to the link's acked base ONLY on positive ack — the receiver's
  mirror therefore equals the sender's acked base bit-exactly, which
  is what makes positional δ reconstruction
  (``reconstruct(kind, mirror, d)``) reproduce the home row
  bit-exactly on arrival;
- promotion is MONOTONIC (a late duplicate ack can never regress the
  watermark), and the acked version per tenant IS the causal
  watermark geo/reads.py certifies local reads against.

Transport discipline is the faults-package stack unchanged: every
exchange runs under :func:`~crdt_tpu.faults.retry.with_retries`
(exponential backoff + the lockstep guard — both ends count rounds,
a mispaired round fails LOUDLY instead of joining mispaired lanes),
the packet stamps the federation generation
(:class:`~crdt_tpu.geo.region.FederationMembership` refuses stale
stamps), and the payload rides under a
:func:`~crdt_tpu.faults.integrity.checksum` digest — a corrupt
inter-region packet is rejected before any join and the retry wrapper
re-ships it (never joins, at-worst heals a round later).
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np

from ..delta_opt.decompose import (
    Decomposition,
    decompose,
    decomposition_bytes,
    reconstruct,
)
from ..faults import integrity
from ..faults.retry import RetryPolicy, with_retries
from ..utils.metrics import metrics
from .region import Federation


class GeoLockstepError(RuntimeError):
    """The two ends of a geo link disagree on the exchange round —
    a mispaired packet would join lanes against the wrong base, so the
    exchange fails loudly instead (the faults/retry.py lockstep
    discipline at federation granularity)."""


class _CorruptPacket(RuntimeError):
    """Receiver-side integrity rejection — raised INSIDE the retried
    exchange so :func:`~crdt_tpu.faults.retry.with_retries` re-ships
    the packet; the corrupt payload itself never joined."""


class GeoPacket(NamedTuple):
    """One anti-entropy shipment: per-tenant δ decompositions over the
    link's acked bases, under a federation-generation stamp, a
    lockstep round, and a whole-payload checksum digest."""

    src: int
    dst: int
    generation: int
    round: int
    tenants: Tuple[int, ...]
    versions: Tuple[int, ...]   # home version each δ brings the mirror to
    deltas: Tuple[Decomposition, ...]
    digest: np.ndarray          # integrity.checksum over the payload


class ExchangeReport(NamedTuple):
    src: int
    dst: int
    tenants_shipped: int
    bytes_delta: float          # δ-lane wire bytes actually shipped
    bytes_full_mirror: float    # what full-state mirroring would have cost
    rejected: int               # integrity rejections healed by retry
    round: int


class GeoLink:
    """Directed per-(src→dst) link state: the host-side ack window."""

    def __init__(self, src: int, dst: int):
        self.src = int(src)
        self.dst = int(dst)
        # tenant -> the sender's shipped copy promoted on positive ack;
        # equals the receiver's mirror bit-exactly (ackwin semantics).
        self.acked_base: Dict[int, object] = {}
        self.acked_ver: Dict[int, int] = {}
        self.round_acked = 0
        self.integrity_rejects = 0

    def watermark(self, tenant: int) -> int:
        return self.acked_ver.get(int(tenant), 0)

    def confirm(self, tenant: int, version: int, shipped_row) -> None:
        """Promote on positive ack — monotonic: a duplicate or
        reordered ack below the current watermark is a no-op."""
        t = int(tenant)
        if version <= self.acked_ver.get(t, 0):
            return
        self.acked_ver[t] = int(version)
        self.acked_base[t] = shipped_row

    def reset(self, tenants) -> None:
        """Forget the ack window for ``tenants`` — the ⊥ re-entry
        (geo/failover.py): δ re-entry from stale acked bases is
        forbidden, the next exchange re-ships full state."""
        for t in tenants:
            self.acked_ver.pop(int(t), None)
            self.acked_base.pop(int(t), None)


def link_for(fed: Federation, src: int, dst: int) -> GeoLink:
    key = (int(src), int(dst))
    lk = fed.links.get(key)
    if lk is None:
        lk = GeoLink(src, dst)
        fed.links[key] = lk
    return lk


def _payload(tenants, versions, deltas, src, dst, generation, round_):
    """The digest-covered view of a packet: header ints ride as one
    array so a flipped tenant id or round is as detectable as a
    flipped lane byte."""
    hdr = np.asarray(
        [src, dst, generation, round_] + list(tenants) + list(versions),
        np.int64,
    )
    return (hdr, tuple(deltas))


def _tree_shapes_match(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        x.shape == y.shape and x.dtype == y.dtype
        for x, y in zip(la, lb)
    )


def _materialized_row(plane, tenant: int):
    """The receiver's (or sender's) current host row for a tenant:
    resident lane, else restore-on-touch from the durable tier, else
    ⊥. Returns ``None`` only when restore fails outright."""
    sb = plane.sb
    t = int(tenant)
    if not sb.is_resident(t):
        if plane.evictor is not None and sb.was_evicted[t]:
            plane.evictor.restore(t)
    if sb.is_resident(t):
        return sb.row(t)
    return jax.tree.map(np.asarray, sb.empty_row())


def build_packet(
    fed: Federation, src: int, dst: int, *,
    max_tenants: Optional[int] = None,
) -> Tuple[Optional[GeoPacket], Dict[int, object], float, float]:
    """Assemble one src→dst shipment: src-homed tenants in dst's
    local-interest set whose home version has advanced past the
    link's acked watermark. Returns ``(packet-or-None, shipped
    copies, δ bytes, full-mirror baseline bytes)``; the shipped
    copies are retained sender-side for promote-on-ack."""
    src_plane = fed.plane(src)
    dst_plane = fed.plane(dst)
    link = link_for(fed, src, dst)
    interest = dst_plane.interest_tenants()
    queue = src_plane.queue

    cands: List[int] = []
    for t in sorted(interest):
        if fed.rmap.home(t) != src:
            continue
        applied = int(fed.versions[t]) - len(queue.pending.get(t, ()))
        if applied > link.watermark(t):
            cands.append(t)
        if max_tenants is not None and len(cands) >= max_tenants:
            break
    if not cands:
        return None, {}, 0.0, 0.0

    tenants, versions, deltas = [], [], []
    shipped: Dict[int, object] = {}
    bytes_delta = 0.0
    bytes_full = 0.0
    for t in cands:
        row = _materialized_row(src_plane, t)
        since = link.acked_base.get(t)
        if since is None or not _tree_shapes_match(since, row):
            if since is not None:
                metrics.count("geo.resyncs")
            since = jax.tree.map(np.asarray, src_plane.sb.empty_row())
        d = decompose(fed.kind, row, since)
        applied = int(fed.versions[t]) - len(queue.pending.get(t, ()))
        tenants.append(int(t))
        versions.append(applied)
        deltas.append(d)
        shipped[int(t)] = row
        bytes_delta += float(decomposition_bytes(d))
        bytes_full += float(src_plane.sb.row_nbytes())

    round_ = link.round_acked + 1
    digest = integrity.checksum(_payload(
        tenants, versions, deltas, src, dst,
        fed.membership.generation, round_,
    ))
    pkt = GeoPacket(
        src=int(src), dst=int(dst),
        generation=fed.membership.generation, round=round_,
        tenants=tuple(tenants), versions=tuple(versions),
        deltas=tuple(deltas), digest=np.asarray(digest),
    )
    return pkt, shipped, bytes_delta, bytes_full


def apply_packet(fed: Federation, pkt: GeoPacket) -> List[Tuple[int, int]]:
    """Receiver side: refuse stale generations, hold the lockstep
    round, verify the checksum BEFORE any join, then reconstruct each
    δ over the local mirror (bit-exact by the ack-window invariant)
    and land it. Returns the positive acks ``[(tenant, version)]``."""
    fed.membership.require(pkt.generation, op="exchange")
    plane = fed.plane(pkt.dst)

    last = plane.rounds_applied.get(pkt.src, 0)
    if pkt.round not in (last, last + 1):
        raise GeoLockstepError(
            f"geo link {pkt.src}->{pkt.dst} shipped round {pkt.round} "
            f"but the receiver last applied {last} — mispaired "
            f"exchange; refusing to join"
        )

    if not bool(integrity.verify(
        _payload(pkt.tenants, pkt.versions, pkt.deltas,
                 pkt.src, pkt.dst, pkt.generation, pkt.round),
        pkt.digest,
    )):
        link = link_for(fed, pkt.src, pkt.dst)
        link.integrity_rejects += 1
        metrics.count("geo.integrity_rejects")
        raise _CorruptPacket(
            f"geo packet {pkt.src}->{pkt.dst} round {pkt.round} failed "
            f"its checksum — rejected before join"
        )

    acks: List[Tuple[int, int]] = []
    for t, ver, d in zip(pkt.tenants, pkt.versions, pkt.deltas):
        mirror = _materialized_row(plane, t)
        rec = reconstruct(fed.kind, mirror, d)
        plane.sb.write_row(int(t), jax.tree.map(np.asarray, rec))
        acks.append((int(t), int(ver)))
    plane.rounds_applied[pkt.src] = pkt.round
    return acks


def exchange(
    fed: Federation, src: int, dst: int, *,
    policy: Optional[RetryPolicy] = None,
    transport: Optional[Callable[[GeoPacket], GeoPacket]] = None,
    max_tenants: Optional[int] = None,
) -> ExchangeReport:
    """One retry-wrapped src→dst anti-entropy round. ``transport``
    (identity by default) is the DCN seam — fault-injection tests
    wrap it to drop, delay, or corrupt packets; every failure mode
    lands in :func:`~crdt_tpu.faults.retry.with_retries`' ledger with
    ``last_good`` = the link's last fully-acked round."""
    from .. import obs

    link = link_for(fed, src, dst)
    pkt, shipped, bytes_delta, bytes_full = build_packet(
        fed, src, dst, max_tenants=max_tenants,
    )
    if pkt is None:
        return ExchangeReport(src, dst, 0, 0.0, 0.0, 0, link.round_acked)

    send = transport or (lambda p: p)
    rejects_before = link.integrity_rejects
    pol = policy or RetryPolicy(attempts=3, base_delay=0.0, jitter=0.0)

    def _one_exchange():
        return apply_packet(fed, send(pkt))

    acks = with_retries(
        _one_exchange, pol,
        op=f"geo.exchange.{src}->{dst}", last_good=link.round_acked,
    )
    for t, ver in acks:
        link.confirm(t, ver, shipped[t])
    link.round_acked = pkt.round

    rejected = link.integrity_rejects - rejects_before
    fed.exchanges += 1
    fed.exchange_bytes += bytes_delta
    fed.full_mirror_bytes += bytes_full
    metrics.count("geo.exchanges")
    metrics.count("geo.exchange_bytes", int(bytes_delta))
    obs.emit(
        "geo_exchange", src=int(src), dst=int(dst),
        tenants=len(pkt.tenants), bytes=int(bytes_delta),
        rejected=int(rejected), round=int(pkt.round),
    )
    return ExchangeReport(
        src, dst, len(pkt.tenants), bytes_delta, bytes_full,
        rejected, pkt.round,
    )


def exchange_all(
    fed: Federation, *,
    policy: Optional[RetryPolicy] = None,
    transport: Optional[Callable[[GeoPacket], GeoPacket]] = None,
    max_tenants: Optional[int] = None,
) -> List[ExchangeReport]:
    """One full federation anti-entropy sweep: every live home region
    feeds every OTHER live region's interest set."""
    reports: List[ExchangeReport] = []
    live = sorted(
        r for r, p in fed.planes.items() if p.alive
    )
    for src in live:
        for dst in live:
            if src == dst:
                continue
            reports.append(exchange(
                fed, src, dst, policy=policy, transport=transport,
                max_tenants=max_tenants,
            ))
    return reports


# ---- observability registration (crdt_tpu.analysis) -----------------------

from ..analysis.registry import register_obs_event as _reg_ev  # noqa: E402

_reg_ev(
    "geo_exchange", subsystem="geo",
    fields=("src", "dst", "tenants", "bytes", "rejected", "round"),
    module=__name__,
)
