#!/usr/bin/env python
"""Validate observability exports against the committed schema.

``tools/telemetry_schema.json`` is the contract for everything the
exporter emits: JSONL lines from ``crdt_tpu.exporter.drain_jsonl`` /
``bench.py --metrics-out`` (snapshot / telemetry / span records) and
bare registry snapshots (``metrics.snapshot()``, including the copy
embedded in the bench headline's ``metrics`` field). This checker is
deliberately dependency-free (no jsonschema on the CI image) and runs
as a fast tier-1 test (tests/test_telemetry_schema.py), so exporter
drift — a renamed field, a stringly-typed counter, a NaN smuggled into
a gauge — fails CI instead of silently corrupting trajectories.

CLI::

    python tools/check_telemetry_schema.py out.jsonl [more.jsonl ...]

exits non-zero listing every violating line. Importable surface:
``validate_record`` / ``validate_snapshot`` / ``validate_jsonl``.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Any, List

SCHEMA_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "telemetry_schema.json")


def load_schema(path: str = SCHEMA_PATH) -> dict:
    with open(path) as f:
        return json.load(f)


def _is_int(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _is_number(v: Any) -> bool:
    # Strict JSON numbers only: bools are ints in Python but not
    # numbers here, and NaN/inf do not survive strict JSON round-trips.
    return (
        (_is_int(v) or isinstance(v, float))
        and not isinstance(v, bool)
        and math.isfinite(v)
    )


def _check(value: Any, kind: str, where: str, schema: dict) -> List[str]:
    errs: List[str] = []
    if kind == "string":
        if not isinstance(value, str):
            errs.append(f"{where}: expected string, got {type(value).__name__}")
    elif kind == "int":
        if not _is_int(value):
            errs.append(f"{where}: expected int, got {value!r}")
    elif kind == "number":
        if not _is_number(value):
            errs.append(f"{where}: expected finite number, got {value!r}")
    elif kind == "string_or_null":
        if value is not None and not isinstance(value, str):
            errs.append(f"{where}: expected string or null, got {value!r}")
    elif kind == "object":
        if not isinstance(value, dict):
            errs.append(f"{where}: expected object, got {type(value).__name__}")
    elif kind == "histogram":
        errs += _check_histogram(value, where)
    elif kind.startswith("array:"):
        inner = kind.split(":", 1)[1]
        if not isinstance(value, list):
            errs.append(
                f"{where}: expected array, got {type(value).__name__}"
            )
        else:
            for i, v in enumerate(value):
                errs += _check(v, inner, f"{where}[{i}]", schema)
    elif kind == "gauge":
        if not isinstance(value, dict):
            errs.append(f"{where}: expected gauge object, got {value!r}")
        else:
            for field, fkind in schema["gauge"].items():
                if field not in value:
                    errs.append(f"{where}.{field}: missing")
                else:
                    errs += _check(value[field], fkind, f"{where}.{field}", schema)
    elif kind.startswith("map:"):
        inner = kind.split(":", 1)[1]
        if not isinstance(value, dict):
            errs.append(f"{where}: expected object, got {type(value).__name__}")
        else:
            for k, v in value.items():
                if not isinstance(k, str):
                    errs.append(f"{where}: non-string key {k!r}")
                errs += _check(v, inner, f"{where}[{k!r}]", schema)
    else:  # schema bug, not data bug — still surface it
        errs.append(f"{where}: unknown schema kind {kind!r}")
    return errs


def _check_histogram(value: Any, where: str) -> List[str]:
    """Structural validation of one serialized histogram (the
    ``hist_*`` Telemetry fields — crdt_tpu/obs/hist.py ``to_dict``
    form): strictly-ascending finite edges, non-negative int counts
    EXACTLY one longer than the edges (the trailing count is the
    unbounded +Inf bucket), finite total. Stricter than the generic
    ``array:`` kinds because a shape-valid histogram with mismatched
    lengths silently mis-renders every quantile downstream."""
    errs: List[str] = []
    if not isinstance(value, dict):
        return [f"{where}: expected histogram object, got "
                f"{type(value).__name__}"]
    edges = value.get("edges")
    counts = value.get("counts")
    total = value.get("total")
    if not isinstance(edges, list) or not all(
        _is_number(e) for e in edges
    ):
        errs.append(f"{where}.edges: expected array of finite numbers")
    elif any(b <= a for a, b in zip(edges, edges[1:])):
        errs.append(f"{where}.edges: must be strictly ascending")
    if not isinstance(counts, list) or not all(
        _is_int(c) and c >= 0 for c in counts
    ):
        errs.append(
            f"{where}.counts: expected array of non-negative ints"
        )
    elif isinstance(edges, list) and len(counts) != len(edges) + 1:
        errs.append(
            f"{where}.counts: expected {len(edges) + 1} buckets "
            f"(len(edges) + 1, the last unbounded), got {len(counts)}"
        )
    if not _is_number(total):
        errs.append(f"{where}.total: expected finite number")
    return errs


def validate_record(rec: Any, schema: dict = None) -> List[str]:
    """Errors for one JSONL record (empty list = valid)."""
    schema = schema or load_schema()
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, expected object"]
    rtype = rec.get("record")
    fields = schema["records"].get(rtype)
    if fields is None:
        return [
            f"unknown record type {rtype!r} "
            f"(schema knows {sorted(schema['records'])})"
        ]
    errs: List[str] = []
    for field, kind in fields.items():
        if field not in rec:
            errs.append(f"{rtype}.{field}: missing")
        else:
            errs += _check(rec[field], kind, f"{rtype}.{field}", schema)
    return errs


def validate_snapshot(snap: Any, schema: dict = None) -> List[str]:
    """Errors for a bare ``metrics.snapshot()`` dict (the bench
    headline's ``metrics`` field) — the snapshot record's payload
    without the envelope."""
    schema = schema or load_schema()
    if not isinstance(snap, dict):
        return [f"snapshot is {type(snap).__name__}, expected object"]
    errs: List[str] = []
    errs += _check(snap.get("counters", None), "map:int",
                   "snapshot.counters", schema)
    errs += _check(snap.get("gauges", None), "map:gauge",
                   "snapshot.gauges", schema)
    return errs


def validate_jsonl(path: str, schema: dict = None) -> List[str]:
    """Errors for a whole export file, prefixed ``line N:``."""
    schema = schema or load_schema()
    errs: List[str] = []
    with open(path) as f:
        for n, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as exc:
                errs.append(f"line {n}: not JSON ({exc})")
                continue
            errs += [f"line {n}: {e}" for e in validate_record(rec, schema)]
    return errs


def main(argv: List[str]) -> int:
    if not argv:
        print(__doc__)
        return 2
    schema = load_schema()
    failed = False
    for path in argv:
        errs = validate_jsonl(path, schema)
        if errs:
            failed = True
            print(f"{path}: {len(errs)} schema violation(s)")
            for e in errs[:50]:
                print(f"  {e}")
        else:
            print(f"{path}: OK")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
