"""Opportunistic TPU-evidence capture loop (VERDICT r04 item #1).

Rounds 3 and 4 both lost their TPU artifacts because capture only
happened at round END, when the relay had already been wedged for
hours. This script inverts that: started at round BEGIN, it probes the
relay on a loop, and on the FIRST healthy window runs the full
``tools/run_tpu_checks.py`` battery, saving a timestamped transcript to
``TPU_CHECKS_r05.txt`` and a machine-readable summary to
``TPU_EVIDENCE_r05.json``. Once a passing artifact exists it keeps
re-probing at a slower cadence (fresher evidence is better evidence)
but never overwrites a PASS with a FAIL.

Run it in the background for the whole round:

    python tools/capture_tpu_evidence.py &

State transitions are appended to ``tpu_capture.log``.
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TXT = os.path.join(ROOT, "TPU_CHECKS_r05.txt")
JSN = os.path.join(ROOT, "TPU_EVIDENCE_r05.json")
LOG = os.path.join(ROOT, "tpu_capture.log")

# One full check battery compiles several Mosaic kernels and runs the
# BASELINE-scale legs; give it plenty of rope but not forever.
CHECK_TIMEOUT_S = int(os.environ.get("CAPTURE_CHECK_TIMEOUT", 3000))
RETRY_S = int(os.environ.get("CAPTURE_RETRY", 600))
AFTER_PASS_RETRY_S = int(os.environ.get("CAPTURE_REFRESH", 7200))


def log(msg: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
    with open(LOG, "a") as f:
        f.write(f"{stamp} {msg}\n")


def probe_once(timeout_s: int = 120) -> bool:
    """One subprocess probe (single attempt — the loop IS the retry)."""
    env = dict(os.environ, BENCH_PROBE_ATTEMPTS="1")
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, %r); import bench; "
             "sys.exit(0 if bench.tpu_reachable(timeout_s=%d) else 1)"
             % (ROOT, timeout_s)],
            timeout=timeout_s + 60, capture_output=True, text=True, env=env,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_checks() -> tuple[int, str]:
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "run_tpu_checks.py")],
            timeout=CHECK_TIMEOUT_S, capture_output=True, text=True,
            cwd=ROOT, env=dict(os.environ, BENCH_PROBE_ATTEMPTS="1"),
        )
        return proc.returncode, proc.stdout + "\n--- stderr ---\n" + proc.stderr
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        return -1, f"TIMEOUT after {CHECK_TIMEOUT_S}s\n{out}\n--- stderr ---\n{err}"


def _atomic_write(path: str, content: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(content)
    os.replace(tmp, path)


def main() -> None:
    have_pass = False
    try:
        with open(JSN) as f:
            have_pass = json.load(f).get("ok", False)
    except (OSError, ValueError):
        pass
    log(f"capture loop starting (have_pass={have_pass})")
    while True:
        if not probe_once():
            log("probe: relay unreachable; sleeping")
            time.sleep(RETRY_S)
            continue
        log("probe: relay healthy — running full check battery")
        t0 = time.time()
        rc, transcript = run_checks()
        stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
        ok = rc == 0
        log(f"checks rc={rc} in {time.time()-t0:.0f}s")
        if ok or not have_pass:
            _atomic_write(
                TXT, f"captured_utc: {stamp}\nrc: {rc}\n\n{transcript}\n"
            )
            _atomic_write(
                JSN,
                json.dumps({"ok": ok, "rc": rc, "captured_utc": stamp,
                            "duration_s": round(time.time() - t0, 1),
                            "tail": transcript[-2000:]}, indent=1),
            )
            log(f"artifact written (ok={ok})")
        have_pass = have_pass or ok
        time.sleep(AFTER_PASS_RETRY_S if have_pass else RETRY_S)


if __name__ == "__main__":
    main()
