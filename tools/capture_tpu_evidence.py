"""Checkpointed, opportunistic TPU-evidence capture (VERDICT r04 #1).

Rounds 3 and 4 lost their TPU artifacts to a wedged relay at round end.
Round 5's first loop ran the full ~30-minute ``run_tpu_checks`` battery
on the first healthy window — and the relay tunnel died mid-battery
twice (its MTBF under sustained compile traffic is ~15-25 min), erasing
everything after the first checks. This loop fixes the capture unit:

- Each check is ONE small step (``tools/run_tpu_step.py``), run in its
  own subprocess with its own fresh tunnel and its own timeout.
- Every step result is checkpointed into ``TPU_EVIDENCE_r05.json``
  immediately; a pass is never overwritten by a later failure (the
  failure is recorded alongside as ``last_error`` of a retry).
- Steps run in value order — the flagship config-3 fused-path bench
  first, then the never-yet-green compiled nested-level test, then the
  BASELINE-scale legs — so whatever relay uptime exists buys the most
  important evidence first.
- The loop keeps retrying unpassed steps until all pass, then refreshes
  slowly. ``TPU_CHECKS_r05.txt`` is a rendered summary (status + each
  step's last transcript tail).

Run for the whole round:  python tools/capture_tpu_evidence.py &
State transitions append to ``tpu_capture.log``.
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TXT = os.path.join(ROOT, "TPU_CHECKS_r05.txt")
JSN = os.path.join(ROOT, "TPU_EVIDENCE_r05.json")
LOG = os.path.join(ROOT, "tpu_capture.log")

# (step, timeout_s) in priority order. Timeouts are generous per step
# (a full-scale Mosaic compile over the relay runs 30-90 s; bench legs
# add generation + measurement) but small enough that a hung tunnel
# doesn't eat the round.
STEPS = [
    ("bench_fused", 1200),
    ("mosaic_levels", 900),
    ("config4_map", 1200),
    ("config5_list", 1200),
    ("sparse_1m", 900),
    ("sparse_map_100m", 900),
    ("mosaic_fused", 900),
    ("mosaic_stream", 600),
    ("mosaic_map", 900),
    ("npasses_ab", 900),
    ("entry_compile", 600),
    ("crossover", 900),
]
RETRY_S = int(os.environ.get("CAPTURE_RETRY", 300))
AFTER_PASS_RETRY_S = int(os.environ.get("CAPTURE_REFRESH", 7200))
# Let the relay breathe between consecutive steps — back-to-back
# tunnel churn is what killed the monolithic battery.
STEP_GAP_S = int(os.environ.get("CAPTURE_STEP_GAP", 20))


def log(msg: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
    with open(LOG, "a") as f:
        f.write(f"{stamp} {msg}\n")


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


def probe_once(timeout_s: int = 120) -> bool:
    env = dict(os.environ, BENCH_PROBE_ATTEMPTS="1")
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, %r); import bench; "
             "sys.exit(0 if bench.tpu_reachable(timeout_s=%d) else 1)"
             % (ROOT, timeout_s)],
            timeout=timeout_s + 60, capture_output=True, text=True, env=env,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_step(name: str, timeout_s: int) -> tuple[bool, str]:
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "run_tpu_step.py"),
             name],
            timeout=timeout_s, capture_output=True, text=True, cwd=ROOT,
            env=dict(os.environ, BENCH_PROBE_ATTEMPTS="1"),
        )
        out = proc.stdout + ("\n--- stderr ---\n" + proc.stderr
                             if proc.returncode else "")
        return proc.returncode == 0, out
    except subprocess.TimeoutExpired as e:
        def _s(x):
            return x.decode() if isinstance(x, bytes) else (x or "")
        return False, (f"TIMEOUT after {timeout_s}s\n{_s(e.stdout)}"
                       f"\n--- stderr ---\n{_s(e.stderr)}")


def load_state() -> dict:
    try:
        with open(JSN) as f:
            state = json.load(f)
        if "steps" in state:
            return state
    except (OSError, ValueError):
        pass
    return {"ok": False, "steps": {}}


def _atomic_write(path: str, content: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(content)
    os.replace(tmp, path)


def save_state(state: dict) -> None:
    state["updated_utc"] = _now()
    state["ok"] = all(
        state["steps"].get(n, {}).get("ok") for n, _ in STEPS
    )
    _atomic_write(JSN, json.dumps(state, indent=1))

    lines = [
        f"TPU evidence (round 5) — updated {state['updated_utc']}",
        f"overall: {'ALL CHECKS PASSED' if state['ok'] else 'in progress'}"
        f" ({sum(1 for n, _ in STEPS if state['steps'].get(n, {}).get('ok'))}"
        f"/{len(STEPS)} steps green)",
        "",
        "Each step runs in its own process on the real chip "
        "(tools/run_tpu_step.py); a pass is never overwritten.",
        "",
    ]
    for n, _ in STEPS:
        s = state["steps"].get(n)
        if not s:
            lines.append(f"== {n}: NOT YET RUN")
        elif s.get("ok"):
            lines.append(
                f"== {n}: PASS at {s['utc']} [{s['duration_s']}s]"
                + (f"  (a later retry at {s['retry_utc']} failed: relay)"
                   if s.get("last_error") else "")
            )
            lines.append(s["detail"].rstrip())
        else:
            lines.append(f"== {n}: FAIL at {s['utc']} [{s['duration_s']}s]")
            lines.append((s.get("detail") or "").rstrip()[-1500:])
        lines.append("")
    _atomic_write(TXT, "\n".join(lines))


def main() -> None:
    state = load_state()
    save_state(state)
    log(f"checkpointed capture loop starting "
        f"({sum(1 for n, _ in STEPS if state['steps'].get(n, {}).get('ok'))}"
        f"/{len(STEPS)} already green)")
    while True:
        pending = [(n, t) for n, t in STEPS
                   if not state["steps"].get(n, {}).get("ok")]
        if not pending:
            log("all steps green; sleeping for refresh")
            time.sleep(AFTER_PASS_RETRY_S)
            # Optional freshness: re-run the flagship only; never
            # overwrite its pass on failure.
            n, t = STEPS[0]
            if probe_once():
                t0 = time.time()
                ok, out = run_step(n, t)
                dur = round(time.time() - t0, 1)
                if ok:
                    state["steps"][n] = {
                        "ok": True, "utc": _now(), "duration_s": dur,
                        "detail": out.strip(),
                    }
                    log(f"refreshed {n} in {dur}s")
                else:
                    # Never overwrite the pass; record the failed retry.
                    state["steps"][n]["last_error"] = out.strip()[-500:]
                    state["steps"][n]["retry_utc"] = _now()
                    log(f"refresh of {n} FAILED in {dur}s (pass kept)")
                save_state(state)
            continue
        if not probe_once():
            log("probe: relay unreachable; sleeping")
            time.sleep(RETRY_S)
            continue
        made_progress = False
        for name, timeout_s in pending:
            t0 = time.time()
            ok, out = run_step(name, timeout_s)
            dur = round(time.time() - t0, 1)
            if ok:
                state["steps"][name] = {
                    "ok": True, "utc": _now(), "duration_s": dur,
                    "detail": out.strip(),
                }
                made_progress = True
                log(f"step {name}: PASS in {dur}s")
            else:
                # ``pending`` holds only unpassed steps, so recording
                # the failure can never clobber a pass.
                state["steps"][name] = {
                    "ok": False, "utc": _now(), "duration_s": dur,
                    "detail": out.strip(),
                }
                log(f"step {name}: FAIL in {dur}s")
            save_state(state)
            if not ok:
                # Likely a relay death — stop the sweep, re-probe after
                # a pause instead of burning the queue on a dead tunnel.
                break
            time.sleep(STEP_GAP_S)
        if not made_progress:
            time.sleep(RETRY_S)


if __name__ == "__main__":
    main()
