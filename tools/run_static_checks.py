#!/usr/bin/env python
"""THE static-check suite — one fast tier-1 command chaining every gate.

Sections (each timed, each independently skippable):

- ``lint``      — ``ruff check .`` against the committed ``ruff.toml``
  when a ruff binary/module exists; otherwise the built-in fallback
  linter (F401 unused imports, E722 bare except, E999 syntax errors —
  the highest-signal subset, honoring ``# noqa``) so the gate never
  silently vanishes on images without ruff.
- ``schema``    — the telemetry export contract
  (tools/check_telemetry_schema.py) against a live registry snapshot.
- ``laws``      — the lattice-law engine (crdt_tpu.analysis.laws) over
  every registered merge kind: commutativity / associativity /
  idempotence / identity / δ-inflation, bit-exact on canonical forms.
- ``schedules`` — the bounded SEC model checker
  (crdt_tpu.analysis.schedules): every registered kind converges
  bit-exactly under every delivery schedule up to the bound (reorder,
  duplication, drop-with-resync; causal interleavings for op-based
  kinds), with minimized counterexamples on violation — plus the
  generator-degeneracy gate (a one-point domain vacuates every law).
- ``faults``    — the degraded-mesh fault-tolerance gates
  (crdt_tpu.faults.static_checks): fault-surface registry coverage
  (every public entry exposing ``faults=`` must have registered —
  crdt_tpu.analysis.registry.register_fault_surface), the checksum
  detector (integrity.checksum must catch every injected perturbation
  class), and the eviction-bijection gate (ring_perm stays a true
  bijection under every eviction subset) — each with a committed broken
  twin in analysis/fixtures.py proving the detector fires.
- ``durability``— the crash-consistent durability gates
  (crdt_tpu.durability.static_checks): crashpoint registry coverage
  (every registered durability I/O boundary must be crossed by the
  canonical workload), the kill-then-recover contract at EVERY
  crashpoint (recovery lands the last durable record bit-identically),
  and the broken-twin detector gates — the no-fsync WAL
  (``analysis.fixtures.wal_skips_fsync``) must fail the fsync-policy
  detector and the checksum-ignoring snapshot loader
  (``fixtures.snapshot_load_unchecked``) must fail the corruption
  detector.
- ``decomp``    — the join-irreducible decomposition gates
  (crdt_tpu.delta_opt.static_checks): registry coverage (every merge
  kind must have registered a decomposition —
  crdt_tpu.analysis.registry.register_decomposition, 12/12), the two
  decomposition laws per kind (reconstruction:
  ``join(decompose(s, since)) ⊔ since == s``; irredundancy: no δ lane
  covered by the join of the others — analysis/laws.py), and the
  broken-twin detectors (the lossy and non-irredundant fixtures must
  each fire their law).
- ``wire``     — the fused δ wire gates
  (crdt_tpu.parallel.wire_checks): wire-surface registry coverage
  (every δ ring kind must have a registered codec know function —
  crdt_tpu.analysis.registry.register_wire_surface), the fused-gate
  removal-preservation detector on the committed three-slot fixture
  (the PR 3 wider-gate unsoundness rebuilt IN-KERNEL by
  ``analysis.fixtures.fused_mask_drops_removals`` must fire it), and
  the wire round-trip + checksum-parity + bitmap detectors (the
  word-dropping ``fixtures.bitmap_truncates_lanes`` twin must fire
  the truncation gate).
- ``obs``      — the observability-plane gates
  (crdt_tpu.obs.static_checks): flight-recorder event-type coverage
  (every literal ``emit("...")`` site under ``crdt_tpu/`` must have a
  registered schema — crdt_tpu.analysis.registry.register_obs_event —
  so an event-emitting subsystem cannot ship events a dump header
  cannot describe), the recorder ring-conformance detector (newest
  ``capacity`` events kept in order, every drop counted), and the
  in-kernel histogram conformance detector (jit-folded bucket counts
  bit-exact vs the host reference) — each with a committed broken twin
  in analysis/fixtures.py (``recorder_drops_events``,
  ``histogram_miscounts``) proving the detector fires.
- ``scaleout`` — the elastic mesh scale-out gates
  (crdt_tpu.scaleout.static_checks): scaleout-surface registry
  coverage (every public operational symbol must have registered —
  crdt_tpu.analysis.registry.register_scaleout_surface), the
  generation/bijection membership walk (every admit/drain ring rebuild
  stays a true bijection, generations strictly increase, full
  membership composes NO fault plan), and the broken-twin detector
  gates — the corrupt-blind bootstrap
  (``analysis.fixtures.bootstrap_skips_checksum``) must fail the
  corruption detector and the unacked-blind drain certifier
  (``fixtures.drain_ignores_unacked``) must fail the refusal detector.
- ``serve``    — the multi-tenant serving gates
  (crdt_tpu.serve.static_checks): serve-surface registry coverage
  (every public operational symbol must have registered —
  crdt_tpu.analysis.registry.register_serve_surface), the
  coalesced==sequential-oracle micro A/B + pack/unpack round-trip,
  the rendezvous minimal-remap failover property, and the broken-twin
  detector gate — the dirt-dropping evictor
  (``analysis.fixtures.evictor_drops_dirt``) must fail the
  evict/restore preservation detector.
- ``fanout``   — the δ-subscription fan-out gates
  (crdt_tpu.fanout.static_checks): fanout-surface registry coverage
  (every public operational symbol must have registered —
  crdt_tpu.analysis.registry.register_fanout_surface), the cohort
  wire encode/decode bit-exact round-trip + keep∪defer partition,
  the split-watermark push/replay property, and the broken-twin
  detector gate — the watermark-bucket-skipping pusher
  (``analysis.fixtures.fanout_skips_watermark_bucket``) must fail the
  cohort coverage detector.
- ``federation`` — the geo-federation gates (ISSUE 20,
  crdt_tpu.geo.static_checks): geo-surface registry coverage (every
  public operational symbol must have registered —
  crdt_tpu.analysis.registry.register_geo_surface), the two-region
  convergence micro A/B (mirrors bit-identical to home rows after one
  anti-entropy sweep, δ wire bytes strictly under the full-state
  mirroring baseline, a corrupted packet rejected by the checksum
  lane then healed by the retry re-ship), the watermark-monotonicity
  detector (``crdt_tpu.geo.reads.watermark_reads_sound`` — stale
  local reads labeled stale, certificates monotone, caught-up mirrors
  bit-equal to home), and the broken-twin detector gate — the
  always-fresh read path
  (``analysis.fixtures.region_serves_unwatermarked_read``) must fail
  the watermark detector.
- ``pipeline`` — the pipelined-serving-loop gates (ISSUE 18): the
  skew-aware rebalance minimal-move property (balanced fleet → zero
  moves; every move sheds from an over-threshold host and strictly
  shrinks the gap) on a synthetic zipf load.
- ``concurrency`` — the host-concurrency analysis plane (ISSUE 19):
  effect inference over the serving surface with TOTAL shared-field
  coverage (crdt_tpu.analysis.effects — a mutated-but-unregistered
  field fails discovery), the declared happens-before contracts
  (crdt_tpu.analysis.concur.HB_CONTRACTS — WAL≺dispatch, now migrated
  here from ``pipeline``; the settled persist window; persist≺clear;
  pin≺gather…dispatch; the ack clamp; requeue seq preservation;
  touch≺pick), the cross-thread conflict gate (every conflicting
  effect pair on a shared field ordered by a contract or lock guard),
  the retry-timeout-reaches-collective and thread-discipline lints,
  and the deterministic interleaving explorer
  (crdt_tpu.analysis.interleave — every ≤2-preemption schedule of the
  serve and fanout worlds bit-identical to the serial oracle). Five
  committed broken twins (``UnorderedWalLoop``, ``PersistFreesLanes``,
  ``regressing_ack_promoter_cls``, ``RogueCounterMutator``,
  ``racy_fanout_world`` — the rebuilt PR 16 lane-eviction race) are
  each proven to fire.
- ``jit-lint``  — the jaxpr walker (crdt_tpu.analysis.jit_lint) over
  every registered mesh entry point: traced-branch, unstable-sort,
  float-accum, dtype-overflow, donation-alias, PLUS the collective-
  semantics checks (ppermute bijection, collective axis-name vs the
  entry's registered mesh axes, donated-read-after-collective) and the
  δ digest-gate removal-preservation fixtures — plus registry
  discovery (an unregistered public ``mesh_*`` entry is a failure).
- ``cost``      — the static cost/residency budget gate
  (crdt_tpu.analysis.cost): estimated peak live bytes / collective
  bytes moved / eqn count per entry vs the committed
  ``tools/cost_budgets.json``; >10% regression fails.
  ``--write-budgets`` re-baselines the table instead of checking.
- ``slo``       — the trace-plane/SLO gates (crdt_tpu.obs.trace +
  crdt_tpu.analysis.slo): trace-stage registry coverage (every literal
  ``stamp("...")`` site under ``crdt_tpu/`` must have registered —
  crdt_tpu.analysis.registry.register_trace_stage), the tracer
  conformance detector (canonical journey completes, stamps monotonic,
  latencies bit-equal to ``derive_latencies``) with its two committed
  broken twins (``analysis.fixtures.tracer_skips_stage``,
  ``fixtures.tracer_clock_regresses``) proving it fires, and the
  committed ``tools/slo_budgets.json`` freshness regression gate over
  the deterministic canonical serve+fanout workload (counts exact,
  latency quantiles >10% regression fails; ``--write-budgets``
  re-baselines).
- ``aliasing``  — the compiled-HLO input_output_alias gate
  (tools/check_aliasing.py) over every registered donating entry.

CLI::

    python tools/run_static_checks.py              # everything, rc != 0 on any error
    python tools/run_static_checks.py --only laws,jit-lint
    python tools/run_static_checks.py --skip lint
    python tools/run_static_checks.py --json-out static_checks.json
    python tools/run_static_checks.py --only cost --write-budgets

``--json-out`` writes the machine-readable per-section summary
(pass/fail, finding counts, wall-clock — crdt_tpu.analysis.report) so
CI can trend the gates instead of parsing text.

The jax-heavy sections share one process (and the repo's persistent XLA
compilation cache at .jax_cache/), so a warm run of the whole suite
stays inside the 120 s budget in ISSUE 7's acceptance criteria.
"""

from __future__ import annotations

import argparse
import ast
import os
import subprocess
import sys
import time
from typing import List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SECTIONS = (
    "lint", "schema", "laws", "schedules", "faults", "decomp",
    "durability", "scaleout", "obs", "wire", "serve", "fanout",
    "federation", "pipeline", "concurrency", "jit-lint", "cost",
    "slo", "aliasing",
)

# Directories the fallback linter walks (ruff takes its own config).
LINT_TARGETS = ("crdt_tpu", "tools", "tests", "examples", "bench.py")


# ---- section: lint -------------------------------------------------------

def _noqa_lines(src: str) -> dict:
    """line number -> set of noqa'd codes ('*' = bare noqa). Codes may
    be followed by free-text commentary (``# noqa: F401  (reason)``)."""
    import re

    out = {}
    for i, line in enumerate(src.splitlines(), 1):
        if "# noqa" not in line:
            continue
        tail = line.split("# noqa", 1)[1]
        codes = set(re.findall(r"[A-Z]+[0-9]+", tail)) if (
            tail.lstrip().startswith(":")
        ) else set()
        out[i] = codes or {"*"}
    return out


def _mini_lint_file(path: str) -> List[str]:
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: E999 {exc.msg}"]
    noqa = _noqa_lines(src)

    def quiet(lineno: int, code: str) -> bool:
        codes = noqa.get(lineno, ())
        return "*" in codes or code in codes

    errs: List[str] = []
    imports: List[Tuple[str, int]] = []
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                imports.append(
                    (al.asname or al.name.split(".")[0], node.lineno)
                )
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for al in node.names:
                if al.name == "*":
                    continue
                imports.append((al.asname or al.name, node.lineno))
        elif isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            if not quiet(node.lineno, "E722"):
                errs.append(f"{path}:{node.lineno}: E722 bare except")
    # Names exported via __all__ count as used.
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(getattr(t, "id", "") == "__all__"
                        for t in node.targets)):
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    used.add(c.value)
    if os.path.basename(path) != "__init__.py":  # __init__ = re-export surface
        for name, lineno in imports:
            if name not in used and not quiet(lineno, "F401"):
                errs.append(f"{path}:{lineno}: F401 unused import '{name}'")
    return errs


def mini_lint(targets=LINT_TARGETS) -> List[str]:
    errs: List[str] = []
    for target in targets:
        target = os.path.join(ROOT, target)
        if os.path.isfile(target):
            errs += _mini_lint_file(target)
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    errs += _mini_lint_file(os.path.join(dirpath, fn))
    return errs


def _ruff_cmd():
    import shutil

    if shutil.which("ruff"):
        return ["ruff"]
    try:
        import ruff  # noqa: F401

        return [sys.executable, "-m", "ruff"]
    except ImportError:
        return None


def run_lint() -> List[str]:
    cmd = _ruff_cmd()
    if cmd is not None:
        proc = subprocess.run(
            cmd + ["check", ROOT], capture_output=True, text=True
        )
        if proc.returncode == 0:
            return []
        return (proc.stdout + proc.stderr).strip().splitlines()
    return [f"(ruff unavailable — built-in F401/E722/E999 subset) {e}"
            for e in mini_lint()] or []


# ---- section: schema -----------------------------------------------------

def run_schema() -> List[str]:
    from crdt_tpu.utils.metrics import metrics

    from check_telemetry_schema import validate_snapshot

    metrics.count("static_checks.runs")
    metrics.observe("static_checks.heartbeat", 1.0)
    return validate_snapshot(metrics.snapshot())


# ---- sections: laws / schedules / jit-lint / cost / aliasing --------------

def run_laws():
    from crdt_tpu.analysis import laws

    return laws.check_all()


def run_schedules():
    from crdt_tpu.analysis import schedules

    return schedules.check_all_schedules()


def run_faults():
    from crdt_tpu.faults import static_checks

    return static_checks()


def run_decomp():
    from crdt_tpu.delta_opt import static_checks

    return static_checks()


def run_durability():
    from crdt_tpu.durability import static_checks

    return static_checks()


def run_scaleout():
    from crdt_tpu.scaleout import static_checks

    return static_checks()


def run_obs():
    from crdt_tpu.obs import static_checks

    return static_checks()


def run_wire():
    from crdt_tpu.parallel.wire_checks import static_checks

    return static_checks()


def run_serve():
    from crdt_tpu.serve import static_checks

    return static_checks()


def run_fanout():
    from crdt_tpu.fanout import static_checks

    return static_checks()


def run_federation():
    from crdt_tpu.geo import static_checks

    return static_checks()


def run_pipeline():
    """The pipelined-serving-loop section (ISSUE 18): the skew-aware
    rebalance minimal-move property on a synthetic zipf load (balanced
    fleet plans zero moves; every planned move sheds from an
    over-threshold host and strictly shrinks the src/dst gap). The
    WAL-before-dispatch ordering gate that used to live here is now
    the first ``HB_CONTRACTS`` entry of the ``concurrency`` section.
    """
    from crdt_tpu.analysis.report import Finding
    from crdt_tpu.serve import (
        TenantShardMap, host_loads, rebalance_plan,
    )

    findings = []

    # Rebalance minimal-move property on a synthetic zipf load:
    # 64 tenants, zipf-ish weights, rendezvous placement over 4 hosts.
    sm = TenantShardMap(4)
    tenants = list(range(64))
    weights = {t: 1.0 / (t + 1) ** 1.0 for t in tenants}  # zipf α=1
    loads0 = host_loads(sm, tenants, weights)
    mean = sum(loads0.values()) / len(loads0)
    plan = rebalance_plan(sm, tenants, weights, threshold=1.5)
    loads = dict(loads0)
    for mv in plan:
        if loads[mv.src] <= 1.5 * mean:
            findings.append(Finding(
                "pipeline-rebalance-minimal", f"tenant {mv.tenant}",
                f"move sheds from host {mv.src} whose load "
                f"{loads[mv.src]:.3f} is already under threshold — "
                "not a minimal-move plan",
            ))
        if loads[mv.dst] + mv.load >= loads[mv.src]:
            findings.append(Finding(
                "pipeline-rebalance-minimal", f"tenant {mv.tenant}",
                "move does not strictly shrink the src/dst gap",
            ))
        loads[mv.src] -= mv.load
        loads[mv.dst] += mv.load
    # A balanced fleet (uniform weights) must plan ZERO moves... unless
    # rendezvous itself landed it lopsided, in which case every move
    # still obeys the shed-from-hot rule checked above.
    flat = {t: 1.0 for t in tenants}
    lf = host_loads(sm, tenants, flat)
    if max(lf.values()) <= 1.5 * (sum(lf.values()) / len(lf)):
        if rebalance_plan(sm, tenants, flat, threshold=1.5):
            findings.append(Finding(
                "pipeline-rebalance-minimal", "uniform load",
                "a balanced fleet planned moves — the planner churns "
                "placements it cannot improve",
            ))
    return findings


def run_concurrency():
    """The host-concurrency section (ISSUE 19 tentpole): effect
    inference over the serving surface with total shared-field
    coverage (a mutated-but-unregistered field fails discovery), the
    ``analysis.concur.HB_CONTRACTS`` checker (every declared
    happens-before edge proven executable — WAL≺dispatch, the settled
    persist window, persist≺clear, pin≺gather…dispatch, the ack
    clamp, requeue seq preservation, touch≺pick), the cross-thread
    conflict gate (every conflicting effect pair on a shared field
    ordered by a contract or lock guard), the retry-timeout and
    thread-discipline lints, and the deterministic interleaving
    explorer: bit-identity to the serial oracle on every
    ≤2-preemption schedule of the serve and fanout worlds. Each
    committed broken twin must fire its detector; the rebuilt PR 16
    lane-eviction race must yield a counterexample."""
    from crdt_tpu.analysis import concur, effects, fixtures, interleave
    from crdt_tpu.analysis.report import Finding

    findings = []

    # 1. Coverage: every shared-state mutation on the host surface is
    # registered...
    for field, site in effects.unregistered_shared_mutations():
        findings.append(Finding(
            "concurrency-coverage", field,
            f"shared-state mutation at {site} has no "
            "register_shared_field declaration — its cross-thread "
            "conflicts are invisible to the HB checker",
        ))
    # ...and the unregistered-mutator twin must fail discovery.
    if not effects.unregistered_shared_mutations(
        extra=(fixtures.RogueCounterMutator,)
    ):
        findings.append(Finding(
            "broken-fixture-missed", "RogueCounterMutator",
            "an unregistered shared-field mutation PASSED discovery — "
            "the coverage contract is not actually total",
        ))

    # 2. Declared happens-before contracts, each an executable proof.
    for cname, viol in concur.check_hb_contracts():
        findings.append(Finding("concurrency-hb", cname, viol))
    # Broken twins per contract family: ordering, ack clamp.
    if not concur.call_order_violations(
        fixtures.UnorderedWalLoop, ("_log",), ("_issue",)
    ):
        findings.append(Finding(
            "broken-fixture-missed", "UnorderedWalLoop",
            "the dispatch-before-WAL loop twin PASSED the generalized "
            "call-order scan",
        ))
    if not concur.ack_window_probe(fixtures.regressing_ack_promoter_cls()):
        findings.append(Finding(
            "broken-fixture-missed", "regressing_ack_promoter",
            "an unclamped ack promotion PASSED the ack-window probe",
        ))

    # 3. Conflict gate: every cross-thread conflicting effect pair on
    # a shared field is ordered...
    for viol in concur.uncovered_conflicts():
        findings.append(Finding("concurrency-conflict", "effects", viol))
    # ...and the off-thread lane-freeing twin must be reported.
    if not concur.uncovered_conflicts(
        extra=(fixtures.PersistFreesLanes,),
        extra_threads={"PersistFreesLanes": ("persist",)},
    ):
        findings.append(Finding(
            "broken-fixture-missed", "PersistFreesLanes",
            "a persist-thread lane-table write with no ordering "
            "contract PASSED the conflict gate",
        ))

    # 4. Host lints: no timed retry may reach a collective; every
    # thread is daemon, named, and a registered effect source.
    for viol in concur.retry_timeout_collective_violations():
        findings.append(Finding("concurrency-retry", "retry", viol))
    for viol in concur.thread_lint_violations():
        findings.append(Finding("concurrency-thread", "threads", viol))

    # 5. The interleaving explorer: serve world (dense; the sparse
    # kind and the heavier matrices run in tests/test_concur.py) and
    # fanout world, all ≤2-preemption schedules bit-identical to the
    # serial oracle.
    for mk, preempt in (
        (lambda: interleave.serve_world("orswot"), 1),
        (interleave.fanout_world, 2),
    ):
        r = interleave.explore(mk, preemptions=preempt)
        if not r.ok:
            cx = r.counterexample
            findings.append(Finding(
                "concurrency-interleave", r.world,
                f"schedule {list(cx.schedule)} diverged: "
                + "; ".join(cx.reasons[:2]),
            ))
    # The rebuilt PR 16 lane-eviction race must produce a
    # counterexample within 2 preemptions.
    r = interleave.explore(fixtures.racy_fanout_world, preemptions=2)
    if r.ok:
        findings.append(Finding(
            "broken-fixture-missed", "racy_fanout_world",
            "the lane-eviction-race twin PASSED every explored "
            "schedule — the explorer is not catching the PR 16 race",
        ))
    return findings


def run_jit_lint():
    from crdt_tpu.analysis.jit_lint import check_gates, lint_entry_points

    return lint_entry_points() + check_gates()


def run_cost(write_budgets: bool = False):
    from crdt_tpu.analysis import cost

    if write_budgets:
        measured = cost.write_budgets()
        print(f"     wrote {len(measured)} entry budgets -> "
              f"{os.path.relpath(cost.BUDGET_PATH, ROOT)}")
        return []
    return cost.check_budgets()


def run_slo(write_budgets: bool = False):
    """The trace-plane/SLO section: stamp-site registry coverage
    (every literal ``stamp("...")`` stage under crdt_tpu/ must be
    registered), tracer conformance with both committed broken twins
    proven to fire, and the committed ``tools/slo_budgets.json``
    freshness regression gate."""
    from crdt_tpu.analysis import fixtures, slo
    from crdt_tpu.analysis.registry import unregistered_trace_stages
    from crdt_tpu.analysis.report import Finding
    from crdt_tpu.obs import trace

    findings = []
    for name, where in unregistered_trace_stages():
        findings.append(Finding(
            "slo-stage-coverage", name,
            f"trace stage stamped at {where} has no registration "
            "(register_trace_stage) — the SLO waterfall cannot place "
            "the leg it bounds",
        ))
    if not trace.tracer_conformant(trace.Tracer):
        findings.append(Finding(
            "slo-tracer-conformance", "Tracer",
            "the tracer orphaned, double-completed, or mis-derived a "
            "canonical two-tenant journey (conformance probe)",
        ))
    if trace.tracer_conformant(fixtures.tracer_skips_stage):
        findings.append(Finding(
            "slo-tracer-conformance", "fixtures.tracer_skips_stage",
            "the durable-stamp-dropping broken twin PASSED the tracer "
            "conformance detector — the detector has no teeth",
        ))
    if trace.tracer_conformant(fixtures.tracer_clock_regresses):
        findings.append(Finding(
            "slo-tracer-conformance", "fixtures.tracer_clock_regresses",
            "the regressing-clock broken twin PASSED the tracer "
            "conformance detector — the detector has no teeth",
        ))
    if write_budgets:
        measured = slo.write_budgets()
        print(f"     wrote {len(measured)} SLO baselines -> "
              f"{os.path.relpath(slo.SLO_BUDGET_PATH, ROOT)}")
        return findings
    return findings + slo.check_budgets()


def run_aliasing() -> List[str]:
    import check_aliasing

    return [
        f"{kind}: {detail}"
        for kind, ok, detail in check_aliasing.check_all()
        if not ok
    ]


RUNNERS = {
    "lint": run_lint,
    "schema": run_schema,
    "laws": run_laws,
    "schedules": run_schedules,
    "faults": run_faults,
    "decomp": run_decomp,
    "durability": run_durability,
    "scaleout": run_scaleout,
    "obs": run_obs,
    "wire": run_wire,
    "serve": run_serve,
    "fanout": run_fanout,
    "federation": run_federation,
    "pipeline": run_pipeline,
    "concurrency": run_concurrency,
    "jit-lint": run_jit_lint,
    "cost": run_cost,
    "slo": run_slo,
    "aliasing": run_aliasing,
}

_JAX_SECTIONS = (
    "laws", "schedules", "faults", "decomp", "durability", "scaleout",
    "obs", "wire", "serve", "fanout", "federation", "pipeline",
    "concurrency", "jit-lint", "cost", "slo", "aliasing",
)


def _as_findings(section: str, result):
    """Normalize a runner's result (Finding list or legacy string list)
    into Findings so every section reports uniformly."""
    from crdt_tpu.analysis.report import Finding

    out = []
    for item in result:
        if isinstance(item, Finding):
            out.append(item)
        else:
            out.append(Finding(section, section, str(item)))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", default="", help="comma-separated sections")
    ap.add_argument("--skip", default="", help="comma-separated sections")
    ap.add_argument(
        "--json-out", default="",
        help="write the machine-readable per-section summary "
        "(crdt_tpu.analysis.report) to this path",
    )
    ap.add_argument(
        "--write-budgets", action="store_true",
        help="re-baseline tools/cost_budgets.json and "
        "tools/slo_budgets.json instead of checking (the cost/slo "
        "sections' tile_sweep --write-table flow)",
    )
    args = ap.parse_args(argv)

    only = {s for s in args.only.split(",") if s}
    skip = {s for s in args.skip.split(",") if s}
    unknown = (only | skip) - set(SECTIONS)
    if unknown:
        ap.error(f"unknown sections {sorted(unknown)}; know {SECTIONS}")
    chosen = [
        s for s in SECTIONS
        if (not only or s in only) and s not in skip
    ]

    if any(s in chosen for s in _JAX_SECTIONS):
        # One CPU pin + one persistent compile cache for every jax
        # section (mirrors tests/conftest.py) — this is what keeps the
        # warm full suite inside the 120 s budget. The two vars default
        # INDEPENDENTLY: an ambient JAX_PLATFORMS=cpu (common in CI
        # images) must not silently collapse the virtual mesh to one
        # device — the gates would then lint/price a 1×1 program while
        # the committed budgets and HLO pins assume the 4×2 gate mesh.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        os.environ.setdefault(
            "JAX_COMPILATION_CACHE_DIR", os.path.join(ROOT, ".jax_cache")
        )
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2"
        )

    from crdt_tpu.analysis.report import (
        Finding, SectionResult, errors, write_summary,
    )

    rc = 0
    results: List[SectionResult] = []
    t_all = time.perf_counter()
    for section in chosen:
        t0 = time.perf_counter()
        try:
            if section == "cost":
                found = run_cost(write_budgets=args.write_budgets)
            elif section == "slo":
                found = run_slo(write_budgets=args.write_budgets)
            else:
                found = RUNNERS[section]()
            findings = _as_findings(section, found)
        except Exception as exc:  # a crashed section is a failed gate
            findings = [Finding(
                "section-crash", section,
                f"section crashed: {type(exc).__name__}: {exc}",
            )]
        dt = time.perf_counter() - t0
        res = SectionResult(name=section, findings=findings, seconds=dt)
        results.append(res)
        bad = errors(findings)
        status = "PASS" if not bad else "FAIL"
        print(f"{status} {section:<10} ({dt:5.1f}s)")
        for f in findings:
            print(f"     {f}")
        if bad:
            rc = 1
    if args.json_out:
        write_summary(results, args.json_out)
        print(f"summary -> {args.json_out}")
    print(f"{'OK' if rc == 0 else 'FAILED'} static checks "
          f"({time.perf_counter() - t_all:.1f}s)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
