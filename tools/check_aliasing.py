#!/usr/bin/env python
"""Tier-1 gate: the donated mesh entry points must keep their zero-copy
``input_output_alias`` lowering.

Donation (``donate=True`` on the ring/gossip mesh entry points —
parallel/anti_entropy.py, parallel/delta_ring.py) is what lets the
gossip family run with ONE resident copy of the state instead of two:
the jit donates (state[, dirty]) and XLA aliases the ``[P, ...]``
outputs onto the input buffers. That property is easy to lose silently
— an output reshaped, an extra pad, a spec drift, and XLA quietly
falls back to copying (with nothing but a warning at trace time). This
gate fails CI instead.

For every covered entry point it builds a minimal R == P replica batch
(join identities — aliasing is a property of shapes and shardings, not
content), runs the entry once with ``donate=True``, then checks BOTH
halves of the contract on the memoised jit:

- the StableHLO lowering marks every expected donated input
  (``tf.aliasing_output`` when jax resolves the alias itself,
  ``jax.buffer_donor`` when it defers the pairing to XLA — committed
  shardings take the second path), and
- the compiled module's HLO actually establishes
  ``input_output_alias`` — the authoritative evidence the zero-copy
  program survived compilation.

CLI::

    python tools/check_aliasing.py      # prints one line per entry, rc=1
                                        # on any loss

Importable surface: ``check_all()`` → list of (kind, ok, detail).
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# Shapes: tiny, E divisible by the element axis, R == P so the ring
# outputs alias (anti_entropy._ring_donate_argnums).
E, A, D = 8, 4, 4
K1, K2, M = 4, 2, 2


def _mesh():
    import jax

    from crdt_tpu.parallel import make_mesh

    n = len(jax.devices())
    p = max(n // 2, 1)
    return make_mesh(p, n // p if p else 1)


def _cases(mesh):
    """(kind, run) per donated entry point; run() must execute the
    entry with donate=True on a fresh R == P batch and return the args
    to re-lower the memoised jit with."""
    import jax.numpy as jnp

    from crdt_tpu.ops import map as map_ops
    from crdt_tpu.ops import map3 as m3_ops
    from crdt_tpu.ops import map_map as mm_ops
    from crdt_tpu.ops import map_orswot as mo_ops
    from crdt_tpu.ops import orswot as ops
    from crdt_tpu.ops import sparse_mvmap as smv
    from crdt_tpu.ops import sparse_orswot as sp
    from crdt_tpu import parallel as par
    from crdt_tpu.parallel.mesh import REPLICA_AXIS

    p = mesh.shape[REPLICA_AXIS]

    def dense():
        return ops.empty(E, A, D, batch=(p,))

    def delta_args(state, e):
        dirty = jnp.zeros((p, e), bool)
        fctx = jnp.zeros((p, e, A), state.top.dtype if hasattr(state, "top")
                         else jnp.uint32)
        return dirty, fctx

    def case_gossip():
        s = dense()
        par.mesh_gossip(s, mesh, local_fold="tree", donate=True)
        return (dense(),)

    def case_gossip_map():
        mk = lambda: map_ops.empty(E, A, 2, D, batch=(p,))
        par.mesh_gossip_map(mk(), mesh, donate=True)
        return (mk(),)

    def case_gossip_mo():
        mk = lambda: mo_ops.empty(K1, M, A, D, batch=(p,))
        par.mesh_gossip_map_orswot(mk(), mesh, donate=True)
        return (mk(),)

    def case_gossip_nested():
        mk = lambda: mm_ops.empty(K1, K2, A, 2, D, batch=(p,))
        par.mesh_gossip_nested_map(mk(), mesh, donate=True)
        return (mk(),)

    def case_gossip_map3():
        mk = lambda: m3_ops.empty(K1, K2, M, A, D, batch=(p,))
        par.mesh_gossip_map3(mk(), mesh, donate=True)
        return (mk(),)

    def case_gossip_sparse():
        mk = lambda: sp.empty(E, A, D, 8, batch=(p,))
        par.mesh_gossip_sparse(mk(), mesh, donate=True)
        return (mk(),)

    def case_gossip_smv():
        mk = lambda: smv.empty(E, A, D, 8, batch=(p,))
        par.mesh_gossip_sparse_mvmap(mk(), mesh, donate=True)
        return (mk(),)

    def case_delta():
        s = dense()
        d, f = delta_args(s, E)
        par.mesh_delta_gossip(s, d, f, mesh, local_fold="tree", donate=True)
        s = dense()
        return (s, *delta_args(s, E))

    def case_delta_map():
        mk = lambda: map_ops.empty(E, A, 2, D, batch=(p,))
        s = mk()
        d, f = delta_args(s, E)
        par.mesh_delta_gossip_map(s, d, f, mesh, donate=True)
        s = mk()
        return (s, *delta_args(s, E))

    def case_delta_mo():
        mk = lambda: mo_ops.empty(K1, M, A, D, batch=(p,))
        s = mk()
        d, f = delta_args(s, K1 * M)
        par.mesh_delta_gossip_map_orswot(s, d, f, mesh, donate=True)
        s = mk()
        return (s, *delta_args(s, K1 * M))

    def case_delta_m3():
        mk = lambda: m3_ops.empty(K1, K2, M, A, D, batch=(p,))
        s = mk()
        d, f = delta_args(s, K1 * K2 * M)
        par.mesh_delta_gossip_map3(s, d, f, mesh, donate=True)
        s = mk()
        return (s, *delta_args(s, K1 * K2 * M))

    return [
        ("orswot_gossip", case_gossip, 1),
        ("map_gossip", case_gossip_map, 1),
        ("map_orswot_gossip", case_gossip_mo, 1),
        ("nested_map_gossip", case_gossip_nested, 1),
        ("map3_gossip", case_gossip_map3, 1),
        ("sparse_gossip", case_gossip_sparse, 1),
        ("sparse_mvmap_gossip_s4", case_gossip_smv, 1),
        ("delta_gossip", case_delta, 2),
        ("map_delta_gossip", case_delta_map, 2),
        ("map_orswot_delta_gossip", case_delta_mo, 2),
        ("map3_delta_gossip", case_delta_m3, 2),
    ]


def _donating_fn(kind: str, n_donated: int):
    """The memoised donating jit for ``kind`` (anti_entropy._FN_CACHE;
    donate_argnums is the 4th key element by construction)."""
    from crdt_tpu.parallel import anti_entropy as ae

    hits = [
        fn for key, fn in ae._FN_CACHE.items()
        if key[0] == kind and key[3] == tuple(range(n_donated))
    ]
    return hits[-1] if hits else None


def check_all():
    """Run every case; returns [(kind, ok, detail)]."""
    import jax

    mesh = _mesh()
    results = []
    for kind, run, n_donated in _cases(mesh):
        try:
            args = run()
            fn = _donating_fn(kind, n_donated)
            if fn is None:
                results.append(
                    (kind, False, "no donating jit cached — donation "
                     "was dropped before lowering")
                )
                continue
            low = fn.lower(*args)
            txt = low.as_text()
            n_leaves = sum(
                len(jax.tree.leaves(args[i])) for i in range(n_donated)
            )
            marked = txt.count("tf.aliasing_output") + txt.count(
                "jax.buffer_donor"
            )
            if marked < n_leaves:
                results.append(
                    (kind, False,
                     f"lowering marks {marked}/{n_leaves} donated leaves")
                )
                continue
            compiled = low.compile().as_text()
            if "input_output_alias" not in compiled:
                results.append(
                    (kind, False,
                     "compiled HLO has no input_output_alias — XLA "
                     "dropped the donation (output no longer matches "
                     "the input layout?)")
                )
                continue
            results.append((kind, True, f"{marked} donated leaves alias"))
        except Exception as exc:  # a broken case is a failed gate, loudly
            results.append((kind, False, f"{type(exc).__name__}: {exc}"))
    return results


def main() -> int:
    results = check_all()
    rc = 0
    for kind, ok, detail in results:
        print(f"{'PASS' if ok else 'FAIL'} {kind:<28} {detail}")
        if not ok:
            rc = 1
    return rc


if __name__ == "__main__":
    if "XLA_FLAGS" not in os.environ and "JAX_PLATFORMS" not in os.environ:
        # Standalone invocation on a dev box: mirror the test suite's
        # 8-virtual-device CPU pin so meshes exist without hardware.
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    sys.exit(main())
