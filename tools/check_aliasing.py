#!/usr/bin/env python
"""Tier-1 gate: the donated mesh entry points must keep their zero-copy
``input_output_alias`` lowering.

Donation (``donate=True`` on the ring/gossip mesh entry points —
parallel/anti_entropy.py, parallel/delta_ring.py) is what lets the
gossip family run with ONE resident copy of the state instead of two:
the jit donates (state[, dirty]) and XLA aliases the ``[P, ...]``
outputs onto the input buffers. That property is easy to lose silently
— an output reshaped, an extra pad, a spec drift, and XLA quietly
falls back to copying (with nothing but a warning at trace time). This
gate fails CI instead.

Coverage is REGISTRY-DRIVEN (crdt_tpu.analysis.registry): every mesh
entry point self-registers its cache kind, example-args builder, and
donation arity next to its definition, so a newly added entry point is
picked up here automatically — and a public ``mesh_*`` symbol that
forgot to register is itself a FAILURE row (discovery), not a silent
coverage gap. (Before PR 4 this file hardcoded an 11-entry list.)

For every registered donating entry point it builds a minimal R == P
replica batch (join identities — aliasing is a property of shapes and
shardings, not content), runs the entry once with ``donate=True``, then
checks BOTH halves of the contract on the memoised jit:

- the StableHLO lowering marks every expected donated input
  (``tf.aliasing_output`` when jax resolves the alias itself,
  ``jax.buffer_donor`` when it defers the pairing to XLA — committed
  shardings take the second path), and
- the compiled module's HLO actually establishes
  ``input_output_alias`` — the authoritative evidence the zero-copy
  program survived compilation.

CLI::

    python tools/check_aliasing.py      # prints one line per entry, rc=1
                                        # on any loss

Importable surface: ``check_all()`` → list of (kind, ok, detail).
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _mesh():
    import jax

    from crdt_tpu.parallel import make_mesh

    n = len(jax.devices())
    p = max(n // 2, 1)
    return make_mesh(p, n // p if p else 1)


def _donating_fn(kind: str, n_donated: int):
    """The memoised donating jit for ``kind`` — ONE home for the cache
    key layout assumption (crdt_tpu.analysis.jit_lint)."""
    from crdt_tpu.analysis.jit_lint import _cached_entry_fn

    return _cached_entry_fn(kind, n_donated)


def check_all():
    """Run every registered donating entry point; returns
    [(kind, ok, detail)]. Unregistered-but-public mesh entry points are
    failure rows too."""
    import jax

    from crdt_tpu.analysis.registry import (
        entry_points,
        unregistered_entry_points,
    )

    mesh = _mesh()
    results = []
    for name in unregistered_entry_points():
        results.append(
            (name, False, "public mesh entry point not registered with "
             "crdt_tpu.analysis.registry — the gate cannot cover it")
        )
    for ep in entry_points(donatable=True):
        try:
            ep.invoke(mesh, ep.make_args(mesh))
            args = ep.make_args(mesh)
            fn = _donating_fn(ep.kind, ep.n_donated)
            if fn is None:
                results.append(
                    (ep.kind, False, "no donating jit cached — donation "
                     "was dropped before lowering")
                )
                continue
            low = fn.lower(*args)
            txt = low.as_text()
            n_leaves = sum(
                len(jax.tree.leaves(args[i])) for i in range(ep.n_donated)
            )
            marked = txt.count("tf.aliasing_output") + txt.count(
                "jax.buffer_donor"
            )
            if marked < n_leaves:
                results.append(
                    (ep.kind, False,
                     f"lowering marks {marked}/{n_leaves} donated leaves")
                )
                continue
            compiled = low.compile().as_text()
            if "input_output_alias" not in compiled:
                results.append(
                    (ep.kind, False,
                     "compiled HLO has no input_output_alias — XLA "
                     "dropped the donation (output no longer matches "
                     "the input layout?)")
                )
                continue
            results.append((ep.kind, True, f"{marked} donated leaves alias"))
        except Exception as exc:  # a broken case is a failed gate, loudly
            results.append((ep.kind, False, f"{type(exc).__name__}: {exc}"))
    return results


def main() -> int:
    results = check_all()
    rc = 0
    for kind, ok, detail in results:
        print(f"{'PASS' if ok else 'FAIL'} {kind:<28} {detail}")
        if not ok:
            rc = 1
    return rc


if __name__ == "__main__":
    if "XLA_FLAGS" not in os.environ and "JAX_PLATFORMS" not in os.environ:
        # Standalone invocation on a dev box: mirror the test suite's
        # 8-virtual-device CPU pin so meshes exist without hardware.
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    sys.exit(main())
