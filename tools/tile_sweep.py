"""On-chip autotune sweep for the fused fold's (tile_e, r_chunk) grid.

The r3 sweep fixed the VMEM block budget at 1 MiB and the default
tile_e at 512 (ops/pallas_kernels.py `_VMEM_BLOCK_BUDGET`). This tool
re-measures the neighborhood on the real toolchain at the bench
config-3 stream shape so the defaults are evidence, not folklore:

    python tools/tile_sweep.py                # sweep, print a ranked table
    python tools/tile_sweep.py --write-table  # sweep AND commit the winner
                                              # into tools/tile_table.json

For each candidate it times the same marginal K-vs-2K stream bench.py
uses (relay-RTT independent) and reports achieved GB/s. Combos that
fail Mosaic compilation are reported as such and skipped — that is data
too (the 4 MiB block failure is recorded in the kernel's module
docstring). Run only when the chip is free (libtpu is process-exclusive
behind the relay).

``--write-table`` closes the loop that made sweep results write-only:
the best measured (tile_e, r_chunk) for this shape's actor count is
merged into the committed ``tools/tile_table.json``, which
``ops/pallas_kernels._pick_r_chunk`` consults before its VMEM-budget
heuristic — so a committed sweep changes the production default, with
provenance (GB/s, shape, UTC timestamp) riding each entry.
"""

from __future__ import annotations

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# Modest default shape: big enough to be bandwidth-bound, small enough
# that a full sweep fits a relay window. Override via env.
R = int(os.environ.get("SWEEP_REPLICAS", 2048))
E = int(os.environ.get("SWEEP_ELEMS", 32768))
PASSES = int(os.environ.get("SWEEP_PASSES", 4))


def main() -> int:
    import bench

    if not bench.tpu_reachable():
        print("FAIL: no TPU backend reachable")
        return 1
    if "--wire" in sys.argv[1:]:
        return sweep_wire()

    import jax
    import numpy as np

    from crdt_tpu.ops.pallas_kernels import fold_fused

    chunk = bench.make_chunk_on_device(R, E)
    a = chunk.ctr.shape[-1]
    nbytes = chunk.ctr.nbytes + chunk.top.nbytes

    def measure(tile_e: int, r_chunk: int):
        # Warm/compile, correctness vs the default config, then the
        # marginal-stream timing: (2K passes) - (K passes) over the
        # resident chunk isolates pure stream time.
        out, _ = fold_fused(chunk, tile_e=tile_e, r_chunk=r_chunk)
        jax.block_until_ready(out.ctr)

        def run(n):
            o, _ = fold_fused(
                chunk, tile_e=tile_e, r_chunk=r_chunk, n_passes=n
            )
            jax.block_until_ready(o.ctr)

        run(PASSES), run(2 * PASSES)  # compile both pass counts
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            run(PASSES)
            t1 = time.perf_counter()
            run(2 * PASSES)
            t2 = time.perf_counter()
            ts.append((t2 - t1) - (t1 - t0))
        dt = sorted(ts)[1]
        gbps = nbytes * PASSES / dt / 1e9
        mps = (PASSES * R) / dt
        return out, gbps, mps

    from crdt_tpu.ops.pallas_kernels import _pick_r_chunk

    rows = []
    # The shipped default first — it is the bit-identity reference for
    # every other combo AND the "vs default" anchor of the ranking.
    cands = [(512, _pick_r_chunk(R, a, 512, None))]
    for tile_e in (256, 512, 1024, 2048):
        for budget_blocks in (0.5, 1, 2):
            rc = max(8, int(budget_blocks * 1024 * 1024) // (a * tile_e * 4))
            rc = 1 << (rc.bit_length() - 1)
            cands.append((tile_e, rc))
    baseline = None
    seen = set()
    for tile_e, rc in cands:
        if (tile_e, rc) in seen:
            continue
        seen.add((tile_e, rc))
        try:
            out, gbps, mps = measure(tile_e, rc)
        except Exception as e:  # Mosaic rejection or OOM — data, not noise
            msg = str(e).splitlines()[0][:100]
            rows.append((tile_e, rc, None, None, msg))
            print(f"tile_e={tile_e:<5} r_chunk={rc:<4} FAILED: {msg}")
            continue
        if baseline is None:
            baseline = out
        else:
            for x, y in zip(jax.tree.leaves(baseline), jax.tree.leaves(out)):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        rows.append((tile_e, rc, gbps, mps, ""))
        print(
            f"tile_e={tile_e:<5} r_chunk={rc:<4} {gbps:7.1f} GB/s "
            f"{mps:12,.0f} merges/s"
        )

    ok = [r for r in rows if r[2] is not None]
    if not ok:
        print("FAIL: no candidate compiled")
        return 1
    best = max(ok, key=lambda r: r[2])
    print(
        f"BEST: tile_e={best[0]} r_chunk={best[1]} {best[2]:.1f} GB/s "
        f"(all results bit-identical)"
    )
    if "--write-table" in sys.argv[1:]:
        path = write_table(a, best, shape=f"{R}x{E}x{a}")
        print(f"committed tile_e={best[0]} r_chunk={best[1]} -> {path}")
    return 0


def sweep_wire() -> int:
    """Sweep the fused WIRE kernel's row chunk (ops/wire_kernels.py)
    at the bench quick-comms packet shape: one fused pack pass per
    candidate, correctness pinned bit-identical against the default
    chunk, marginal timing over repeated packs. ``--write-table``
    commits the winner under ``family: "wire"`` — the fold family's
    entries are untouched (``_pick_r_chunk`` keys on family)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from crdt_tpu.ops import wire_kernels as wk
    from crdt_tpu.ops.pallas_kernels import _pick_r_chunk

    c = int(os.environ.get("SWEEP_WIRE_SLOTS", 1024))
    a = int(os.environ.get("SWEEP_WIRE_ACTORS", 8))
    lc = 2 * a
    spec = wk.WireLaneSpec(lc=lc, ctx_lo=a, ctx_hi=lc, gated=True)
    rng = np.random.RandomState(0)
    rows = jnp.asarray(rng.randint(0, 50, (c, a)), jnp.uint32)
    ctxs = rows + jnp.asarray(rng.randint(0, 2, (c, a)), jnp.uint32)
    clocks = jnp.concatenate([rows, ctxs], axis=-1)
    base = jnp.zeros_like(clocks)
    valid = jnp.asarray(rng.rand(c) > 0.2)
    dig = jnp.full((c, a), 100, jnp.uint32)

    def run(rc):
        import crdt_tpu.ops.pallas_kernels as pk

        # Pin the candidate by pre-seeding the family lookup: pass the
        # chunk through a one-entry in-memory table override.
        old = pk._TILE_TABLE
        pk._TILE_TABLE = {"entries": [
            {"family": "wire", "a": a, "tile_e": lc, "r_chunk": rc}
        ]}
        try:
            out = wk.wire_pack(
                spec, clocks, base, valid, know=rows, dig=dig,
                interpret=False,
            )
            jax.block_until_ready(out.words)
            return out
        finally:
            pk._TILE_TABLE = old

    default_rc = _pick_r_chunk(c, a, lc, None, family="wire")
    baseline = None
    results = []
    for rc in sorted({default_rc, 64, 128, 256, 512, 1024}):
        rc = min(rc, c)
        try:
            out = run(rc)  # compile + correctness
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(8):
                    jax.block_until_ready(run(rc).words)
                ts.append(time.perf_counter() - t0)
            dt = sorted(ts)[1] / 8
        except Exception as e:
            print(f"r_chunk={rc:<5} FAILED: {str(e).splitlines()[0][:90]}")
            continue
        if baseline is None:
            baseline = out
        else:
            for x, y in zip(baseline, out):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        gbps = clocks.nbytes / dt / 1e9
        results.append((lc, rc, gbps))
        print(f"r_chunk={rc:<5} {gbps:7.1f} GB/s ({dt * 1e6:.1f} us/pack)")
    if not results:
        print("FAIL: no wire candidate compiled")
        return 1
    best = max(results, key=lambda r: r[2])
    print(f"BEST: r_chunk={best[1]} {best[2]:.1f} GB/s "
          f"(all results bit-identical)")
    if "--write-table" in sys.argv[1:]:
        path = write_table(a, best, shape=f"{c}x{lc}", family="wire")
        print(f"committed wire r_chunk={best[1]} -> {path}")
    return 0


TABLE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tile_table.json")


def write_table(a: int, best, shape: str, path: str = TABLE_PATH,
                family: str = "fold") -> str:
    """Merge the winning (tile_e, r_chunk) for actor count ``a`` into
    the committed autotune table — keyed by (kernel FAMILY, a, tile_e),
    so a fused-wire sweep (``--wire``) can never clobber or be reused
    by a fold-family entry (``_pick_r_chunk`` matches families; a
    pre-wire entry with no ``family`` field reads as "fold"). A re-run
    replaces its own previous measurement. Provenance (GB/s, shape,
    UTC timestamp) rides each entry so a stale override is auditable."""
    try:
        with open(path) as f:
            table = json.load(f)
    except (OSError, ValueError):
        table = {"version": 1, "entries": []}
    entries = [
        e for e in table.get("entries", [])
        if not (e.get("family", "fold") == family
                and e.get("a") == a and e.get("tile_e") == best[0])
    ]
    entries.append({
        "family": family,
        "a": a,
        "tile_e": best[0],
        "r_chunk": best[1],
        "gbps": round(best[2], 1),
        "shape": shape,
        "swept_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    })
    table["entries"] = sorted(
        entries,
        key=lambda e: (e.get("family", "fold"), e.get("a", 0),
                       e.get("tile_e", 0)),
    )
    table.setdefault("version", 1)
    with open(path, "w") as f:
        json.dump(table, f, indent=2)
        f.write("\n")
    return path


if __name__ == "__main__":
    sys.exit(main())
