"""Measure the dense↔sparse ORSWOT crossover (SURVEY §7.3).

For a fixed live-dot budget C, the dense join costs O(E·A) HBM traffic
regardless of sparsity while the segment join costs O(C log² C) sort
work — so there is an element-universe size E* past which sparse wins.
This tool times both joins over a sweep of E at constant C — as
chip-side MARGINAL per-join cost (a fori_loop chain of n joins in one
dispatch, t(2n) − t(n), so the relay's fixed round-trip cancels) — and
prints the measured crossover:

    python tools/sparse_crossover.py              # on the TPU
    JAX_PLATFORMS=cpu python tools/sparse_crossover.py --cpu   # scaled

Synthetic states: R=2 replicas, C live dots each scattered uniformly
over E elements in disjoint actor lanes — the worst case for survival
masking: ALL 2C dots survive the join, so the sparse dot capacity is
sized 2C (lossless; the overflow flag is asserted clear)."""

from __future__ import annotations

import argparse
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _marginal(join1, xa, xb, n: int | None = None, iters: int = 5) -> float:
    """Chip-side marginal per-join time via the K-vs-2K method (bench.py
    module docstring): a ``fori_loop`` chain of ``n`` joins runs in ONE
    dispatch, so the relay's ~69 ms fixed round-trip — and its async
    dispatch queue, which acks ``block_until_ready`` before the work
    drains and made single-join timings read as low as 0.04 ms — cancel
    in ``t(2n) − t(n)``. The trip count is a traced operand, so both
    lengths share one compile. The n- and 2n-timings interleave within
    one loop (bench.py's convention) so slow relay drift cancels too,
    and a non-positive marginal falls back to the conservative
    ``t(2n)/2n`` bound instead of letting jitter fabricate a 0-ms
    winner."""
    import jax
    import numpy as np
    from jax import lax

    if n is None:
        # The chain exists to amortise the relay round-trip; on CPU
        # there is none, so keep the sweep quick.
        n = 4 if jax.default_backend() == "cpu" else 32

    @jax.jit
    def chain(x, y, k):
        return lax.fori_loop(0, k, lambda i, s: join1(s, y), x)

    def once(k):
        out = chain(xa, xb, k)
        # Scalar device->host fetch: cannot be acked early by the relay.
        return np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0])

    once(n)
    once(2 * n)  # shared compile + warm both trip counts

    t1s, t2s = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        once(n)
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        once(2 * n)
        t2s.append(time.perf_counter() - t0)
    t1 = sorted(t1s)[len(t1s) // 2]
    t2 = sorted(t2s)[len(t2s) // 2]
    dt = t2 - t1
    if dt <= 0:
        print(
            f"  WARNING: non-positive marginal (T(n)={t1*1e3:.1f} ms, "
            f"T(2n)={t2*1e3:.1f} ms); using conservative T(2n)/2n"
        )
        dt = t2 / 2
    return dt / n


def run(sweep=None, dots: int = 4096, actors: int = 8) -> str:
    """Run the sweep in the CURRENT process/backend (callable from
    run_tpu_checks after the chip is initialized). Returns the summary
    line (also printed)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from crdt_tpu.ops import orswot as dense_ops
    from crdt_tpu.ops import sparse_orswot as sp

    if sweep is None:
        sweep = [1 << p for p in range(14, 24)]  # 16k .. 8M

    c, a = dots, actors
    cap = 2 * c  # every dot of both replicas survives (disjoint lanes)
    rng = np.random.default_rng(0)
    print(
        f"backend={jax.default_backend()}  C={c} live dots/replica, "
        f"A={a} actors; dense bytes = 4*E*A per replica, sparse = "
        f"{sp.nbytes(sp.empty(cap, a)):,} fixed (cap {cap})"
    )
    crossover = None
    for e in sweep:
        ctr = np.zeros((2, e, a), np.uint32)
        for r in range(2):
            cells = rng.choice(e, size=c, replace=False)
            lanes = rng.integers(0, a // 2, c) + r * (a // 2)
            ctr[r, cells, lanes] = rng.integers(1, 50, c)
        top = ctr.max(axis=1)
        dense = dense_ops.empty(e, a, deferred_cap=4, batch=(2,))
        dense = dense._replace(top=jnp.asarray(top), ctr=jnp.asarray(ctr))
        da = jax.tree.map(lambda x: x[0], dense)
        db = jax.tree.map(lambda x: x[1], dense)
        t_dense = _marginal(lambda x, y: dense_ops.join(x, y)[0], da, db)

        spstate = sp.from_dense(dense, cap, rm_width=8)
        sa = jax.tree.map(lambda x: x[0], spstate)
        sb = jax.tree.map(lambda x: x[1], spstate)
        joined, of = sp.join(sa, sb)
        assert not bool(jnp.any(of)), "sparse join overflowed — sweep is lossy"
        assert int(joined.valid.sum()) == 2 * c, "survivor count wrong"
        t_sparse = _marginal(lambda x, y: sp.join(x, y)[0], sa, sb)

        flag = "sparse" if t_sparse < t_dense else "dense"
        if crossover is None and t_sparse < t_dense:
            crossover = e
        print(
            f"E={e:>9,}: dense {t_dense*1e3:8.3f} ms/join "
            f"({4*e*a/1e6:8.1f} MB/replica) | sparse {t_sparse*1e3:8.3f} ms/join "
            f"-> {flag}"
        )
    if crossover:
        line = (
            f"crossover: sparse join wins from E ≈ {crossover:,} "
            f"(at {c} live dots, lossless cap {cap})"
        )
    else:
        line = "no crossover within the sweep (dense won throughout)"
    print(line)
    return line


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="pin CPU + scaled sweep")
    ap.add_argument("--dots", type=int, default=4096, help="live dots per replica")
    ap.add_argument("--actors", type=int, default=8)
    args = ap.parse_args()

    sweep = None
    if args.cpu:
        from crdt_tpu.utils.cpu_pin import pin_cpu

        pin_cpu()
        sweep = [1 << p for p in range(12, 21)]  # 4k .. 1M
    run(sweep=sweep, dots=args.dots, actors=args.actors)


if __name__ == "__main__":
    main()
