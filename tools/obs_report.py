#!/usr/bin/env python
"""Render a flight-recorder dump into a postmortem incident report.

A ``crdt_tpu.obs.FlightRecorder.dump()`` artifact is a self-describing
JSONL file: one ``flight_header`` line (format version + registered
event-type schemas), the buffered events (each stamped with the
``(generation, round, rank)`` correlation key), and a final registry
``snapshot``. This tool turns one into something a human on call can
act on:

- **timeline** — the events in order, keyed ``gen/round/rank``, so
  device rounds and host I/O (WAL fsyncs, snapshot commits, membership
  transitions, scale-out votes) read as one story;
- **histogram summaries** — the ``hist_*`` distributions folded across
  every ``telemetry`` event (p50/p95/p99 per kind: apply latency,
  per-round payload bytes, residue backlog, ack-window depth);
- **invariant audit** — cross-event contract checks: a ``telemetry``
  event claiming ``residue == 0`` while the same run lost/rejected
  packets (the PR 8 loss-voids-certificate contract), a frontier lag
  that never decreases across the dump (a straggler pinning
  reclamation), drain refusals with unacked out-lanes, and
  ``telemetry_delta`` sums exceeding the final snapshot (a rewound
  counter), and — when a dirty-tenant serve WAL was active
  (``serve_wal_round`` events) — any client-acked trace completing
  WITHOUT a durable WAL seq at or below the newest logged round
  (acked-op-without-durable-record, the ISSUE 18 loss window);
- **counter cross-check** — the dump's ``telemetry`` events re-folded
  through ``crdt_tpu.telemetry.counter_increments`` (THE one mapping
  ``telemetry.record`` itself applies) and compared BIT-EXACTLY
  against a registry snapshot — the dump's embedded final snapshot by
  default, or a caller-provided live one (``build_report(path,
  snapshot=metrics.snapshot())`` — what bench legs and
  tests/test_obs.py do). A mismatch means the artifact does not
  faithfully narrate the run it claims to.

- **trace replay** (``--slo``) — the op-journey trace events
  (``trace_stage`` / ``trace_requeue`` / ``trace_complete`` —
  crdt_tpu/obs/trace.py) replayed bit-exactly: every completed
  journey's recorded stamps must equal the stamps its events narrate
  and its latencies must equal ``derive_latencies`` of them, then the
  stage waterfall and submit→client-ack freshness quantiles render.

CLI::

    python tools/obs_report.py flight-....jsonl [--slo] [--json-out report.json]

exits non-zero on parse errors, counter mismatches, audit violations,
or (under ``--slo``) replay mismatches. Importable surface:
``load_dump`` / ``fold_counters`` / ``fold_histograms`` / ``audit`` /
``cross_check`` / ``trace_replay`` / ``build_report`` /
``render_text``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(_HERE)
for p in (ROOT, _HERE):
    if p not in sys.path:
        sys.path.insert(0, p)

from check_telemetry_schema import validate_record  # noqa: E402


def load_dump(path: str) -> Dict[str, Any]:
    """Parse + schema-validate one dump. Returns ``{"header", "events",
    "snapshot", "spans", "errors"}`` — ``errors`` non-empty means the
    artifact is damaged (every line is still read; a postmortem tool
    must salvage what it can)."""
    header = None
    events: List[dict] = []
    spans: List[dict] = []
    snapshot = None
    errors: List[str] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as exc:
        return {"header": None, "events": [], "spans": [],
                "snapshot": None, "errors": [f"unreadable dump: {exc}"]}
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as exc:
            errors.append(f"line {i}: not JSON ({exc})")
            continue
        rtype = rec.get("record") if isinstance(rec, dict) else None
        if rtype in ("flight_header", "flight", "snapshot"):
            for e in validate_record(rec):
                errors.append(f"line {i}: {e}")
        if rtype == "flight_header":
            if header is not None:
                errors.append(f"line {i}: duplicate flight_header")
            header = rec
        elif rtype == "flight":
            events.append(rec)
        elif rtype == "span":
            spans.append(rec)
        elif rtype == "snapshot":
            snapshot = rec  # the LAST snapshot is the final one
        else:
            errors.append(f"line {i}: unknown record {rtype!r}")
    if header is None:
        errors.append("no flight_header record — not a flight dump")
    elif header.get("events") != len(events):
        errors.append(
            f"header claims {header.get('events')} events, dump carries "
            f"{len(events)}"
        )
    if snapshot is None:
        errors.append("no final snapshot record — cross-check impossible")
    return {"header": header, "events": events, "spans": spans,
            "snapshot": snapshot, "errors": errors}


def fold_counters(events: List[dict]) -> Dict[str, int]:
    """Re-fold every ``telemetry`` event through the ONE
    record-to-counter mapping (``telemetry.counter_increments``) —
    what the live registry must bit-exactly agree with."""
    from crdt_tpu.telemetry import counter_increments

    folded: Dict[str, int] = defaultdict(int)
    for ev in events:
        if ev.get("type") != "telemetry":
            continue
        try:
            inc = counter_increments(ev["kind"], ev)
        except (KeyError, TypeError):
            # A telemetry event missing fields cannot fold — the
            # cross-check then reports the registry counters it failed
            # to reproduce, which is the right loud failure.
            continue
        for name, n in inc.items():
            folded[name] += n
    return dict(folded)


def cross_check(
    folded: Dict[str, int], snapshot: Optional[dict],
) -> List[str]:
    """Bit-exact mismatches between the re-folded dump counters and a
    registry snapshot (empty = the artifact faithfully narrates the
    registry). Sound when the registry was reset when recording
    started — the bench legs and the acceptance test do exactly that."""
    if snapshot is None:
        return ["no snapshot to cross-check against"]
    counters = snapshot.get("counters", {})
    out = []
    for name in sorted(folded):
        want, got = folded[name], counters.get(name, 0)
        if want != got:
            out.append(
                f"{name}: dump folds to {want}, registry holds {got}"
            )
    return out


def fold_histograms(events: List[dict]) -> Dict[str, Dict[str, Any]]:
    """Fold the ``hist_*`` fields across every ``telemetry`` event:
    ``{"<kind>.<name>": summary}`` with p50/p95/p99/count/total/mean
    (crdt_tpu.obs.hist.summary) plus the folded counts."""
    from crdt_tpu.obs import hist as obs_hist
    from crdt_tpu.telemetry import HIST_FIELDS

    acc: Dict[str, dict] = {}
    for ev in events:
        if ev.get("type") != "telemetry":
            continue
        for field in HIST_FIELDS:
            hd = ev.get(field)
            if not isinstance(hd, dict) or not sum(hd.get("counts", [])):
                continue
            key = f"{ev['kind']}.{field[len('hist_'):]}"
            slot = acc.setdefault(key, {
                "edges": hd["edges"],
                "counts": [0] * len(hd["counts"]),
                "total": 0.0,
            })
            slot["counts"] = [
                a + b for a, b in zip(slot["counts"], hd["counts"])
            ]
            slot["total"] += hd["total"]
    return {
        k: {**obs_hist.summary(v), "counts": v["counts"]}
        for k, v in acc.items()
    }


def audit(dump: Dict[str, Any]) -> List[Dict[str, str]]:
    """Cross-event invariant findings (``severity`` "error" fails the
    report; "warning" is advisory)."""
    findings: List[Dict[str, str]] = []
    events = dump["events"]

    # 1. Residue certificate vs losses: PR 8's contract is that a lost
    # or rejected packet forces residue >= 1 — a dispatch claiming
    # both a certificate AND losses is narrating the impossible.
    for ev in events:
        if ev.get("type") != "telemetry":
            continue
        lost = ev.get("faults_dropped", 0) + ev.get("faults_rejected", 0)
        if lost > 0 and ev.get("residue", 0) == 0:
            findings.append({
                "check": "residue-certificate-vs-losses",
                "severity": "error",
                "detail": (
                    f"round {ev.get('round')}: kind {ev.get('kind')!r} "
                    f"lost/rejected {lost} packets yet reads residue == 0 "
                    f"— loss must void the certificate"
                ),
            })

    # 2. Frontier-lag stall: a lag that is positive and never
    # decreases across the dump means a straggler pinned reclamation
    # the whole recorded window.
    lags: Dict[str, List[int]] = defaultdict(list)
    for ev in events:
        if ev.get("type") == "telemetry":
            lags[ev["kind"]].append(ev.get("frontier_lag", 0))
    for kind, seq in lags.items():
        if len(seq) >= 3 and seq[0] > 0 and all(
            b >= a for a, b in zip(seq, seq[1:])
        ):
            findings.append({
                "check": "frontier-lag-stall",
                "severity": "warning",
                "detail": (
                    f"kind {kind!r}: frontier lag never decreased across "
                    f"{len(seq)} recorded rounds ({seq[0]} -> {seq[-1]}) "
                    f"— a straggler is pinning reclamation"
                ),
            })

    # 3. Unacked out-lanes: every refused drain in the window, with
    # why — the graceful-exit contract's refusals are the story.
    for ev in events:
        if ev.get("type") == "drain_refused":
            findings.append({
                "check": "drain-refused",
                "severity": "warning",
                "detail": (
                    f"round {ev.get('round')}: drain of rank "
                    f"{ev.get('rank')} refused at generation "
                    f"{ev.get('gen')} — {ev.get('why', '?')} "
                    f"(residue {ev.get('residue')}, lost "
                    f"{ev.get('packets_lost')}, unacked "
                    f"{ev.get('lanes_unacked')})"
                ),
            })

    # 4. Delta monotonicity: telemetry_delta sums can never exceed the
    # final snapshot (counters are monotone); more means a counter was
    # reset mid-flight or the dump mixes processes.
    snapshot = dump.get("snapshot") or {}
    final = snapshot.get("counters", {})
    sums: Dict[str, int] = defaultdict(int)
    for ev in events:
        if ev.get("type") == "telemetry_delta":
            for k, v in (ev.get("counters") or {}).items():
                sums[k] += v
    for k, v in sorted(sums.items()):
        if v > final.get(k, 0):
            findings.append({
                "check": "delta-exceeds-final",
                "severity": "error",
                "detail": (
                    f"{k}: snapshot deltas sum to {v} but the final "
                    f"snapshot holds {final.get(k, 0)} — a counter "
                    f"rewound mid-recording"
                ),
            })

    # Serving/fan-out audits gate on the ring's per-type drop
    # accounting: a dropped boundary event would make either check
    # misnarrate, so both stand down (loudly, via skipped=) when the
    # events they reason over were evicted from the ring.
    header = dump.get("header") or {}
    by_type = header.get("dropped_by_type")

    def _dropped(*etypes) -> bool:
        if by_type is None:  # pre-accounting dump: only the total exists
            return bool(header.get("dropped", 0))
        return any(by_type.get(t, 0) for t in etypes)

    # 5. Eviction discipline: a dispatch trace-stamp touching a tenant
    # BETWEEN its tenant_evicted and tenant_restored events means the
    # serving tier applied ops to a lane it had already released — the
    # restore-on-touch contract (crdt_tpu/serve/evict.py) broken.
    if not _dropped("trace_stage", "tenant_evicted", "tenant_restored"):
        evicted: Dict[Any, bool] = {}
        for ev in events:
            et = ev.get("type")
            if et == "tenant_evicted":
                evicted[ev.get("tenant")] = True
            elif et == "tenant_restored":
                evicted[ev.get("tenant")] = False
            elif (et == "trace_stage" and ev.get("stage") == "dispatch"
                    and evicted.get(ev.get("tenant"))):
                findings.append({
                    "check": "dispatch-while-evicted",
                    "severity": "error",
                    "detail": (
                        f"round {ev.get('round')}: dispatch stamped on "
                        f"tenant {ev.get('tenant')} between its "
                        f"tenant_evicted and tenant_restored events — "
                        f"ops applied to a released lane"
                    ),
                })

    # 6. Fan-out cohort conservation: every fanout_push event's cohort
    # count and the folded telemetry cohorts_per_dispatch counter
    # narrate the same dispatches — their sums must agree whenever the
    # dump carries both signals (a mismatch means one of them was
    # tampered with or a dispatch went unrecorded).
    pushes = [ev for ev in events if ev.get("type") == "fanout_push"]
    tel_cohorts = [
        int(ev.get("cohorts_per_dispatch", 0)) for ev in events
        if ev.get("type") == "telemetry" and "cohorts_per_dispatch" in ev
    ]
    if pushes and any(tel_cohorts) and not _dropped(
        "fanout_push", "telemetry"
    ):
        got = sum(int(ev.get("cohorts", 0)) for ev in pushes)
        want = sum(tel_cohorts)
        if got != want:
            findings.append({
                "check": "fanout-cohort-conservation",
                "severity": "error",
                "detail": (
                    f"fanout-push events narrate {got} cohorts but the "
                    f"folded telemetry cohorts_per_dispatch holds "
                    f"{want} — the dump's push story disagrees with "
                    f"its telemetry"
                ),
            })

    # 7. Acked-op-without-durable-record (ISSUE 18): when the dump
    # shows an active dirty-tenant serve WAL (serve_wal_round events),
    # every completed — i.e. client-ACKED — trace must carry the
    # wal_seq of the group-commit round that made its op durable, and
    # that seq must be at or below the newest logged round. An acked
    # trace with no wal_seq means the ack outran the fsync — exactly
    # the loss window the WAL-before-dispatch ordering exists to close.
    wal_rounds = [ev for ev in events if ev.get("type") == "serve_wal_round"]
    if wal_rounds and not _dropped(
        "trace_complete", "serve_wal_round", "wal_fsync"
    ):
        watermark = max(int(ev.get("seq", -1)) for ev in wal_rounds)
        for ev in events:
            if ev.get("type") != "trace_complete":
                continue
            seq = ev.get("wal_seq")
            if seq is None:
                findings.append({
                    "check": "acked-op-without-durable-record",
                    "severity": "error",
                    "detail": (
                        f"round {ev.get('round')}: trace "
                        f"{ev.get('trace')!r} (tenant {ev.get('tenant')}) "
                        f"completed its ack with NO serve-WAL seq while "
                        f"the WAL was active — the ack outran the fsync"
                    ),
                })
            elif int(seq) > watermark:
                findings.append({
                    "check": "acked-op-without-durable-record",
                    "severity": "error",
                    "detail": (
                        f"round {ev.get('round')}: trace "
                        f"{ev.get('trace')!r} claims WAL seq {seq} but "
                        f"the newest logged round is {watermark} — the "
                        f"durable record it cites does not exist"
                    ),
                })
    return findings


def _rank_quantile(vals: List[int], q: float) -> float:
    """Nearest-rank quantile over EXACT values (the replay holds the
    real latencies, not bucket counts — no interpolation needed)."""
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(q * len(s) + 0.999999) - 1))
    return float(s[idx])


def trace_replay(dump: Dict[str, Any]) -> Dict[str, Any]:
    """Replay the trace plane's events bit-exactly: rebuild every
    sampled op journey from its ``trace_stage`` stamps (a
    ``trace_requeue`` rolls the journey back to its submit stamp —
    exactly what ``Tracer.requeue`` does to the live trace), then
    require each ``trace_complete`` event's recorded stamps to equal
    the replayed ones and its recorded latencies to equal
    ``derive_latencies`` of those stamps (THE one mapping the live
    tracer applies). Also rejects double-completion and post-completion
    stamps. Returns ``{"ok", "mismatches", "traces_completed",
    "stage_waterfall", "freshness", "skipped"}`` — ``skipped`` non-None
    means trace events were dropped from the ring and a bit-exact
    replay would misnarrate (not a failure, but not a proof either)."""
    from crdt_tpu.obs.trace import derive_latencies

    out: Dict[str, Any] = {
        "ok": True, "mismatches": [], "traces_completed": 0,
        "stage_waterfall": {}, "freshness": None, "skipped": None,
    }
    header = dump.get("header") or {}
    by_type = header.get("dropped_by_type")
    if by_type is None:
        lost = int(header.get("dropped", 0))
    else:
        lost = sum(
            int(by_type.get(t, 0))
            for t in ("trace_stage", "trace_requeue", "trace_complete")
        )
    if lost:
        out["skipped"] = (
            f"{lost} trace events dropped from the ring — a bit-exact "
            f"replay would misnarrate; raise the recorder capacity or "
            f"the trace sampling modulus"
        )
        return out

    stamps: Dict[Any, List[list]] = defaultdict(list)
    completed: Dict[Any, dict] = {}
    mism = out["mismatches"]
    for ev in dump["events"]:
        et = ev.get("type")
        tid = ev.get("trace")
        if et == "trace_stage":
            if tid in completed:
                mism.append(
                    f"trace {tid}: stage {ev.get('stage')!r} stamped "
                    f"AFTER trace_complete — a completed journey moved"
                )
                continue
            stamps[tid].append([ev.get("stage"), int(ev.get("t_ns", 0))])
        elif et == "trace_requeue":
            stamps[tid] = stamps[tid][:1]
        elif et == "trace_complete":
            if tid in completed:
                mism.append(f"trace {tid}: completed twice")
                continue
            completed[tid] = ev
            got = stamps.get(tid, [])
            want = [[s, int(t)] for s, t in (ev.get("stamps") or [])]
            if got != want:
                mism.append(
                    f"trace {tid}: replayed stamps {got} != recorded "
                    f"stamps {want}"
                )
            lat = derive_latencies(want)
            rec_lat = {
                k: int(v) for k, v in (ev.get("lat") or {}).items()
            }
            if rec_lat != lat:
                mism.append(
                    f"trace {tid}: recorded latencies {rec_lat} != "
                    f"derive_latencies(stamps) {lat}"
                )
    out["traces_completed"] = len(completed)
    legs: Dict[str, List[int]] = defaultdict(list)
    for ev in completed.values():
        for k, v in (ev.get("lat") or {}).items():
            legs[k].append(int(v))
    for k, vals in sorted(legs.items()):
        s = {
            "count": len(vals),
            "p50": _rank_quantile(vals, 0.50),
            "p95": _rank_quantile(vals, 0.95),
            "p99": _rank_quantile(vals, 0.99),
        }
        if k == "freshness_us":
            out["freshness"] = s
        else:
            out["stage_waterfall"][k] = s
    out["ok"] = not mism
    return out


def build_report(
    path: str, snapshot: Optional[dict] = None, slo: bool = False,
) -> Dict[str, Any]:
    """The full machine-readable report. ``snapshot`` overrides the
    dump's embedded final snapshot as the cross-check target (pass the
    LIVE ``metrics.snapshot()`` to prove the dump reproduces the live
    registry — the ISSUE 12 acceptance flow). ``slo`` adds the trace
    replay (:func:`trace_replay`) under ``report["slo"]`` and folds its
    verdict into ``ok``."""
    dump = load_dump(path)
    folded = fold_counters(dump["events"])
    target = snapshot if snapshot is not None else dump["snapshot"]
    mismatches = cross_check(folded, target)
    findings = audit(dump)
    hard = [f for f in findings if f["severity"] == "error"]
    replay = trace_replay(dump) if slo else None
    report = {
        "path": path,
        "ok": (not dump["errors"] and not mismatches and not hard
               and (replay is None or replay["ok"])),
        "parse_errors": dump["errors"],
        "counter_mismatches": mismatches,
        "audit": findings,
        "histograms": fold_histograms(dump["events"]),
        "events": len(dump["events"]),
        "dropped": (dump["header"] or {}).get("dropped", 0),
        "reason": (dump["header"] or {}).get("reason", ""),
        "folded_counters": folded,
    }
    if replay is not None:
        report["slo"] = replay
    return report


def _brief(ev: dict) -> str:
    skip = {"record", "type", "ts", "gen", "round", "rank"}
    parts = []
    for k, v in ev.items():
        if k in skip:
            continue
        if isinstance(v, dict):
            v = f"<{len(v)} keys>"
        elif isinstance(v, list):
            v = f"<{len(v)} items>"
        parts.append(f"{k}={v}")
        if len(parts) >= 5:
            parts.append("...")
            break
    return " ".join(parts)


def render_text(report: Dict[str, Any], dump: Optional[dict] = None,
                max_events: int = 60) -> str:
    """The human-readable incident report."""
    lines = [
        f"flight dump: {report['path']}",
        f"reason: {report['reason'] or 'manual'} | events: "
        f"{report['events']} (dropped {report['dropped']})",
        f"verdict: {'OK' if report['ok'] else 'VIOLATIONS FOUND'}",
    ]
    if report["parse_errors"]:
        lines.append("\nparse errors:")
        lines += [f"  ! {e}" for e in report["parse_errors"]]
    if dump is None:
        dump = load_dump(report["path"])
    lines.append("\ntimeline (gen/round/rank):")
    events = dump["events"]
    shown = events[-max_events:]
    if len(events) > len(shown):
        lines.append(f"  ... {len(events) - len(shown)} earlier events")
    for ev in shown:
        key = f"g{ev.get('gen', '?')}/r{ev.get('round', '?')}/" \
              f"k{ev.get('rank', '?')}"
        lines.append(f"  [{key:>12}] {ev.get('type', '?'):<22} {_brief(ev)}")
    if report["histograms"]:
        lines.append("\nhistogram summaries:")
        for key, s in sorted(report["histograms"].items()):
            lines.append(
                f"  {key}: n={s['count']} mean={s['mean']:.1f} "
                f"p50={s['p50']:.1f} p95={s['p95']:.1f} p99={s['p99']:.1f}"
            )
    if report["audit"]:
        lines.append("\ninvariant audit:")
        for f in report["audit"]:
            lines.append(
                f"  [{f['severity'].upper()}] {f['check']}: {f['detail']}"
            )
    else:
        lines.append("\ninvariant audit: clean")
    if report["counter_mismatches"]:
        lines.append("\ncounter cross-check: FAILED")
        lines += [f"  ! {m}" for m in report["counter_mismatches"]]
    else:
        lines.append(
            f"\ncounter cross-check: bit-exact "
            f"({len(report['folded_counters'])} counters)"
        )
    if "slo" in report:
        rp = report["slo"]
        if rp["skipped"]:
            lines.append(f"\ntrace replay: SKIPPED — {rp['skipped']}")
        elif rp["mismatches"]:
            lines.append("\ntrace replay: FAILED")
            lines += [f"  ! {m}" for m in rp["mismatches"]]
        else:
            lines.append(
                f"\ntrace replay: bit-exact "
                f"({rp['traces_completed']} journeys)"
            )
            if rp["stage_waterfall"]:
                lines.append("stage waterfall (us):")
                for k, s in rp["stage_waterfall"].items():
                    lines.append(
                        f"  {k:<18} n={s['count']:<6} p50={s['p50']:.0f} "
                        f"p95={s['p95']:.0f} p99={s['p99']:.0f}"
                    )
            if rp["freshness"]:
                s = rp["freshness"]
                lines.append(
                    f"freshness (submit->client-ack, us): "
                    f"n={s['count']} p50={s['p50']:.0f} "
                    f"p95={s['p95']:.0f} p99={s['p99']:.0f}"
                )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="flight-recorder JSONL artifact")
    ap.add_argument(
        "--json-out", default="",
        help="also write the machine-readable report here",
    )
    ap.add_argument(
        "--slo", action="store_true",
        help="replay the trace-plane events bit-exactly and render the "
             "stage waterfall + end-to-end freshness quantiles",
    )
    args = ap.parse_args(argv)
    report = build_report(args.dump, slo=args.slo)
    print(render_text(report), end="")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"report -> {args.json_out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
