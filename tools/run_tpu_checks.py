"""Run the real-chip checks outside pytest (tests/conftest.py pins the
suite to a virtual CPU mesh, so the compiled Mosaic tests there always
skip — this script is how to actually exercise them on hardware):

    python tools/run_tpu_checks.py

Runs, in order: a backend probe (fail-fast on a wedged relay, same
mechanism as bench.py), the compiled fused-fold equality tests (plain
orswot, Map<K, MVReg>, map_orswot + map3 nested levels), the n_passes
streaming-equivalence A/B, the entry() compile check, a scaled
fused-vs-tree bench sanity, the config-4/5/sparse legs, the FLAGSHIP
replica-streaming leg (10,240 x 1M via parallel/stream.py, shape
replayed verbatim from BENCH_CONFIGS.json — degraded or
non-bit-identical fails the check), the SERVE multi-tenant leg
(1M+ live tenants through the tenant-packed superblock, same verbatim-
replay rule — degraded, non-bit-identical, or missing its in-window
evict→restore cycle fails the check), the SERVE_ZIPF pipelined
always-on leg (zipf popularity through the WAL-logged pipelined
ServeLoop with the 10× hot-shard skew event, same verbatim-replay
rule — degraded, non-bit-identical, any acked op lost across
kill/recover, a pipeline that never overlapped, or a during-skew p99
above 1.5× pre-skew fails the check), and the FANOUT δ-subscription
leg (1M+ subscribers pushed cohort δ payloads over the churning
superblock, same verbatim-replay rule — degraded, non-bit-identical,
below the 1M-subscriber / ≥10× δ-vs-full-state gates, or missing its
dead-subscriber resync fails the check)."""

import importlib.util
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def npasses_streaming_ab() -> bool:
    """A/B-verify the bench's n_passes equivalence claim (bench.py module
    docstring): at a shape where K distinct chunks fit in HBM, folding K
    concatenated copies of a chunk (K distinct HBM regions — the real
    stream) must take the same time as K grid re-walks of one resident
    chunk, and produce the same bits (join idempotence). A big gap would
    mean re-walks hit some cache effect and the streamed bench number is
    not an honest distinct-replica number."""
    import jax
    import numpy as np

    import bench

    k_chunks, r, e = 4, 512, 16384
    chunk = bench.make_chunk_on_device(r, e)
    big = jax.tree.map(
        lambda x: jax.numpy.concatenate([x] * k_chunks, axis=0), chunk
    )
    jax.block_until_ready(big.ctr)
    from crdt_tpu.ops.pallas_kernels import fold_fused

    distinct, _ = fold_fused(big)                       # warm + result
    rewalk, _ = fold_fused(chunk, n_passes=k_chunks)    # warm + result
    for a, b in zip(jax.tree_util.tree_leaves(distinct), jax.tree_util.tree_leaves(rewalk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def med(fn, n=5):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            out, _ = fn()
            jax.block_until_ready(out.ctr)
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    t_distinct = med(lambda: fold_fused(big))
    t_rewalk = med(lambda: fold_fused(chunk, n_passes=k_chunks))
    ratio = t_rewalk / t_distinct
    print(
        f"n_passes A/B: distinct {k_chunks}x{r} chunks {t_distinct*1e3:.1f} ms "
        f"vs {k_chunks} re-walks {t_rewalk*1e3:.1f} ms (ratio {ratio:.2f}); "
        f"results bit-identical"
    )
    if not 0.67 <= ratio <= 1.5:
        print("FAIL: re-walk stream is not time-equivalent to distinct chunks")
        return False
    return True


def static_summary_covers_concurrency() -> bool:
    """The chip run rides on the host-side gates having run: the
    ``concurrency`` section (host-interleaving soundness) and the
    ``federation`` section (geo surface coverage + watermark-read
    monotonicity) must be wired into the static-check chain, and any
    committed/CI summary JSON (``static_checks.json``, or
    ``$STATIC_CHECKS_SUMMARY``) must contain their entries — a summary
    that predates a section means the serving runtime on this chip was
    never checked for it."""
    import json

    import run_static_checks as rsc

    required = ("concurrency", "federation")
    for section in required:
        if section not in rsc.SECTIONS or section not in rsc.RUNNERS:
            print(f"FAIL: '{section}' section missing from the "
                  "static-check chain (tools/run_static_checks.py)")
            return False
    path = os.environ.get(
        "STATIC_CHECKS_SUMMARY", os.path.join(ROOT, "static_checks.json")
    )
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        for section in required:
            if section not in doc.get("sections", {}):
                print(f"FAIL: static-check summary {path} has no "
                      f"'{section}' section — rerun "
                      "tools/run_static_checks.py --json-out before the "
                      "chip checks")
                return False
    return True


def main() -> int:
    # bench.py reads the BENCH_* env into module globals at import time,
    # so the scaled sanity shape must be set BEFORE the import.
    os.environ.setdefault("BENCH_REPLICAS", "2048")
    os.environ.setdefault("BENCH_ELEMS", "16384")

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    if not static_summary_covers_concurrency():
        return 1

    import bench

    if not bench.tpu_reachable():
        print("FAIL: no TPU backend reachable (see stderr for the probe)")
        return 1

    import jax

    print(f"backend: {jax.default_backend()}, devices: {jax.devices()}")

    spec = importlib.util.spec_from_file_location(
        "tpc", os.path.join(ROOT, "tests", "test_pallas_compiled.py")
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    for name, label in [
        ("test_fused_fold_compiles_and_matches_tree_on_tpu",
         "compiled fused fold == tree fold"),
        ("test_multi_pass_stream_compiles_on_tpu",
         "multi-pass stream idempotent"),
        ("test_fused_map_fold_compiles_and_matches_tree_on_tpu",
         "compiled MVReg-map fused fold == tree"),
        ("test_fused_level_folds_compile_and_match_tree_on_tpu",
         "compiled mo/map3 fused folds == tree"),
    ]:
        t0 = time.time()
        getattr(m, name)()
        print(f"{label:<35}[{time.time()-t0:.0f}s]")

    if not npasses_streaming_ab():
        return 1

    t0 = time.time()
    import __graft_entry__ as g

    fn, args = g.entry()
    jax.jit(fn).lower(*args).compile()
    print(f"entry() compiles                   [{time.time()-t0:.0f}s]")

    mps, path, gbps, _, shape, relay_bound = bench.bench_tpu()
    print(
        f"bench sanity: {mps:,.0f} merges/s ({path}, {gbps:.0f} GB/s, "
        f"{shape}{', relay-bound' if relay_bound else ''})"
    )
    if path != "fused":
        print("FAIL: fused path did not run on the chip")
        return 1

    os.environ["BENCH_MAP_KEYS"] = os.environ.get("BENCH_MAP_KEYS", "1000000")
    t0 = time.time()
    bench.bench_map()
    print(f"config4 1M-key fused fold ran      [{time.time()-t0:.0f}s]")

    t0 = time.time()
    bench.bench_list()  # BASELINE scale: 100k-op trace x 1024 replicas
    print(f"config5 100kx1024 ran              [{time.time()-t0:.0f}s]")

    t0 = time.time()
    rec = bench.bench_sparse()  # 1M-element universe, segment-encoded
    print(
        f"config-sparse 1M-universe ran       [{time.time()-t0:.0f}s] "
        f"({rec['value']:,.0f} merges/s, {rec['compression']:,.0f}x "
        f"compression)"
    )

    # Observability plane: the chaos leg runs under a flight recorder
    # (bench.py installs one, dumps, and asserts the bit-exact replay
    # itself); here the artifact must additionally PARSE as a valid
    # self-describing dump through tools/obs_report.py — a dump that
    # cannot be loaded postmortem is a failed check even if the leg's
    # numbers were fine.
    t0 = time.time()
    chaos_recs = bench.bench_chaos()
    if chaos_recs:
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        from obs_report import build_report

        chaos = chaos_recs[0]
        flight_report = build_report(chaos["flight_dump"])
        if flight_report["parse_errors"]:
            print(
                f"FAIL: chaos flight dump does not parse: "
                f"{flight_report['parse_errors'][:3]}"
            )
            return 1
        print(
            f"chaos flight dump parsed           [{time.time()-t0:.0f}s] "
            f"({flight_report['events']} events, dispatch p99 "
            f"{chaos.get('dispatch_p99_us', 0):,.0f} us)"
        )
        # The chaos leg now runs over the FUSED wire (PR 14); the leg
        # itself replays the soak on the layered oracle and asserts
        # the degraded states bit-identical — a record that reports
        # otherwise (or that silently fell back to the layered path)
        # is a failed check on real hardware too.
        if not (chaos.get("fused") and chaos.get(
            "fused_vs_layered_identical"
        ) and chaos.get("bit_identical")):
            print("FAIL: chaos leg not fused-bit-identical "
                  f"(fused={chaos.get('fused')}, vs_layered="
                  f"{chaos.get('fused_vs_layered_identical')}, healed="
                  f"{chaos.get('bit_identical')})")
            return 1
        print(
            "chaos fused wire bit-identical     "
            f"(packed {chaos.get('wire_packed_bytes_total', 0):,.0f} B "
            "on the wire)"
        )

    # THE flagship: 10,240 replicas x 1M elements streamed through the
    # mesh (parallel/stream.py), shape replayed VERBATIM from the
    # committed BENCH_CONFIGS.json entry. The record must be clean on
    # hardware — a relay-bound marginal here is a failed check, not a
    # degraded-but-acceptable row.
    t0 = time.time()
    rec = bench.bench_flagship()
    print(
        f"flagship {rec['shape']} streamed    [{time.time()-t0:.0f}s] "
        f"({rec['value']:,.0f} merges/s over {rec['blocks']} blocks, "
        f"resident {rec['resident_reduction']}x below co-resident, "
        f"bit-identity gate {'OK' if rec['bit_identical'] else 'FAILED'})"
    )
    if rec["degraded"] or not rec["bit_identical"]:
        print("FAIL: flagship record degraded or not bit-identical")
        return 1

    # The serving front door: 1M+ live tenants through the tenant-packed
    # superblock, shape replayed VERBATIM from the committed
    # BENCH_CONFIGS.json serve entry. The leg itself asserts the
    # per-tenant sequential-oracle bit-identity and the in-window
    # evict→restore cycle; here a degraded or non-bit-identical record
    # is a failed check on real hardware.
    t0 = time.time()
    serve_recs = bench.bench_serve()
    if serve_recs:
        srv = serve_recs[0]
        print(
            f"serve {srv['tenants']:,} tenants ran  [{time.time()-t0:.0f}s] "
            f"({srv['value']:,.0f} ops/s, dispatch p99 "
            f"{srv['dispatch_p99_us']:,.0f} us, "
            f"{srv['evict_restored_in_window']} evict→restore cycles, "
            f"bit-identity gate {'OK' if srv['bit_identical'] else 'FAILED'})"
        )
        if srv.get("degraded") or not srv["bit_identical"]:
            print("FAIL: serve record degraded or not bit-identical")
            return 1
        if srv["tenants"] < 1_000_000 or srv["evict_restored_in_window"] < 1:
            print("FAIL: serve leg below the 1M-tenant / evict-restore gate")
            return 1

    # The pipelined always-on zipf leg (ISSUE 18), shape replayed
    # VERBATIM from the committed BENCH_CONFIGS.json serve entry's
    # zipf_* knobs. The leg itself asserts oracle + serial-equivalence
    # + kill/recover bit-identity; here a degraded record, any acked op
    # lost across recovery, a pipeline that never overlapped, or a
    # during-skew p99 blown past 1.5× the pre-skew p99 (the rebalance
    # failed to absorb the hot shard) is a failed check on hardware.
    t0 = time.time()
    zipf_recs = bench.bench_serve_zipf()
    if zipf_recs:
        sz = zipf_recs[0]
        print(
            f"serve_zipf ran  [{time.time()-t0:.0f}s] "
            f"({sz['value']:,.0f} ops/s pipelined vs "
            f"{sz['serial_ops_per_sec']:,.0f} serial = "
            f"{sz['pipeline_speedup']}x, overlap "
            f"{sz['overlap_hit_ratio']:.0%}, WAL "
            f"{sz['serve_wal_bytes']:,} B / {sz['serve_wal_fsyncs']} "
            f"fsyncs, p99 {sz['dispatch_p99_before_us']:,.0f}/"
            f"{sz['dispatch_p99_during_us']:,.0f}/"
            f"{sz['dispatch_p99_after_us']:,.0f} us, "
            f"{sz['rebalance_moves']} rebalance moves, "
            f"recovery gate "
            f"{'OK' if sz['recovered_bit_identical'] else 'FAILED'})"
        )
        if sz.get("degraded") or not sz["bit_identical"]:
            print("FAIL: serve_zipf record degraded or not bit-identical")
            return 1
        if sz["acked_ops_lost"] or not sz["recovered_bit_identical"]:
            print("FAIL: serve_zipf lost acked ops across kill/recover")
            return 1
        if sz["overlap_hits"] < 1:
            print("FAIL: serve_zipf pipeline never overlapped host work "
                  "with an in-flight dispatch")
            return 1
        if sz["skew_p99_ratio"] > 1.5:
            print("FAIL: serve_zipf during-skew dispatch p99 exceeds "
                  "1.5x the pre-skew p99 — rebalancing did not absorb "
                  "the hot shard")
            return 1

    # The fan-out egress: 1M+ subscribers pushed cohort δ payloads over
    # the churning superblock, shape replayed VERBATIM from the
    # committed BENCH_CONFIGS.json fanout entry. The leg itself asserts
    # the client-replica bit-identity (sampled live replicas + one
    # revived dead subscriber), the in-window evict→re-warm cycle, and
    # the ≥10× δ-vs-full-state byte gate; here a degraded or
    # non-bit-identical record — or one below the 1M-subscriber /
    # ratio / resync-fallback floors — is a failed check on hardware.
    t0 = time.time()
    fanout_recs = bench.bench_fanout()
    if fanout_recs:
        fo = fanout_recs[0]
        print(
            f"fanout {fo['subscribers']:,} subscribers ran  "
            f"[{time.time()-t0:.0f}s] ({fo['value']:,.0f} δ-pushes/s, "
            f"{fo['bytes_per_subscriber']:,.0f} B/subscriber vs "
            f"{fo['full_row_bytes']:,} B full row = "
            f"{fo['overall_vs_full_ratio']}x overall, "
            f"{fo['resync_fallbacks']} resync fallbacks, bit-identity "
            f"gate {'OK' if fo['bit_identical'] else 'FAILED'})"
        )
        if fo.get("degraded") or not fo["bit_identical"]:
            print("FAIL: fanout record degraded or not bit-identical")
            return 1
        if (fo["subscribers"] < 1_000_000
                or fo["overall_vs_full_ratio"] < 10
                or fo["resync_fallbacks"] < 1):
            print("FAIL: fanout leg below the 1M-subscriber / 10x-δ / "
                  "resync-fallback gate")
            return 1

    # The geo-federation plane: a multi-region mesh-of-meshes replayed
    # VERBATIM from the committed BENCH_CONFIGS.json geo entry — δ
    # anti-entropy over checksum-guarded inter-region links, a
    # mid-traffic region kill re-homed from the durable tier, and
    # causal-watermark local reads. The leg itself asserts the
    # single-mesh-oracle bit-identity, the zero-acked-op-loss gate,
    # the ≤25% cross-region-bytes-vs-full-mirroring gate, and the
    # partial-replication residency bound; here a degraded or failing
    # record is a failed check on hardware.
    t0 = time.time()
    geo_recs = bench.bench_geo()
    if geo_recs:
        g = geo_recs[0]
        print(
            f"geo {g['regions']} regions x {g['tenants']:,} tenants "
            f"ran  [{time.time()-t0:.0f}s] ({g['exchange_bytes']:,.0f} B "
            f"cross-region vs {g['full_mirror_bytes']:,.0f} B "
            f"full-mirror = {g['wire_vs_mirror_pct']:.1f}%, "
            f"{g['failovers']} failover(s), {g['acked_ops_lost']} acked "
            f"ops lost, bit-identity gate "
            f"{'OK' if g['bit_identical'] else 'FAILED'})"
        )
        if g.get("degraded") or not g["bit_identical"]:
            print("FAIL: geo record degraded or not bit-identical to "
                  "the single-mesh oracle")
            return 1
        if g["acked_ops_lost"] or not g["recovered_bit_identical"]:
            print("FAIL: geo region-kill failover lost acked ops")
            return 1
        if g["wire_vs_mirror_pct"] > 25:
            print("FAIL: cross-region δ bytes exceed 25% of full-state "
                  "mirroring")
            return 1
        if not g["resident_bound_ok"]:
            print("FAIL: partial replication violated — a region's "
                  "resident lanes exceed its home+interest tenant set")
            return 1

    # In-process (libtpu is exclusive per process — a subprocess could
    # not reach the already-initialized chip).
    t0 = time.time()
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from sparse_crossover import run as crossover_run

    line = crossover_run()
    print(f"sparse crossover: {line}   [{time.time()-t0:.0f}s]")

    print("ALL TPU CHECKS PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
