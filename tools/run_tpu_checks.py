"""Run the real-chip checks outside pytest (tests/conftest.py pins the
suite to a virtual CPU mesh, so the compiled Mosaic tests there always
skip — this script is how to actually exercise them on hardware):

    python tools/run_tpu_checks.py

Runs, in order: a backend probe (fail-fast on a wedged relay, same
mechanism as bench.py), the compiled fused-fold equality tests, the
entry() compile check, and a scaled fused-vs-tree bench sanity."""

import importlib.util
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> int:
    # bench.py reads the BENCH_* env into module globals at import time,
    # so the scaled sanity shape must be set BEFORE the import.
    os.environ.setdefault("BENCH_REPLICAS", "2048")
    os.environ.setdefault("BENCH_ELEMS", "16384")
    import bench

    if not bench.tpu_reachable():
        print("FAIL: no TPU backend reachable (see stderr for the probe)")
        return 1

    import jax

    print(f"backend: {jax.default_backend()}, devices: {jax.devices()}")

    spec = importlib.util.spec_from_file_location(
        "tpc", os.path.join(ROOT, "tests", "test_pallas_compiled.py")
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    t0 = time.time()
    m.test_fused_fold_compiles_and_matches_tree_on_tpu()
    print(f"compiled fused fold == tree fold   [{time.time()-t0:.0f}s]")
    t0 = time.time()
    m.test_multi_pass_stream_compiles_on_tpu()
    print(f"multi-pass stream idempotent       [{time.time()-t0:.0f}s]")

    t0 = time.time()
    import __graft_entry__ as g

    fn, args = g.entry()
    jax.jit(fn).lower(*args).compile()
    print(f"entry() compiles                   [{time.time()-t0:.0f}s]")

    mps, path, gbps, _, shape = bench.bench_tpu()
    print(f"bench sanity: {mps:,.0f} merges/s ({path}, {gbps:.0f} GB/s, {shape})")
    if path != "fused":
        print("FAIL: fused path did not run on the chip")
        return 1
    print("ALL TPU CHECKS PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
