"""Reference-mount inventory check (SURVEY.md §0 provenance caveat).

SURVEY.md was reconstructed with the reference mount EMPTY, and its §0
mandates: "run `ls /root/reference/src`; if the mount is populated,
re-verify this inventory". This script is that step as a CI-runnable
tool:

    python tools/check_reference.py [--reference DIR] [--out FILE]

- Mount absent/empty: records that fact in the evidence artifact
  (REFERENCE_CHECK.json by default) and exits 0 — SURVEY.md stays the
  blueprint of record.
- Mount populated: inventories `src/*.rs`, diffs against the module
  files SURVEY.md cites, and writes both directions of the delta
  (cited-but-missing / present-but-uncited) plus per-file line counts
  so a reviewer can upgrade SURVEY.md citations to file:line. Exits 1
  on any delta so CI surfaces the drift.

It also inventories every ``[LOW-CONF …]`` reference marker in the
package docstrings and records each one's AUDIT status (the committed
:data:`_LOW_CONF_AUDIT` table — verified against SURVEY.md §3, ISSUE 7
satellite): with the mount absent every audited marker is
**blueprint-only** (the survey is itself low-confidence on that symbol,
so there is nothing to upgrade against); a populated mount turns every
low-conf marker into an upgrade work item (rc 1) alongside the module
delta; a marker the audit table does not know is flagged *unaudited*
so new guesses cannot slip in silently.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Survey rows that are section/test globs, not src/ module files.
_NON_MODULES = {"build.rs"}

_LOW_CONF_RE = re.compile(r"\[LOW-CONF[^\]]*\]")

#: The committed audit (ISSUE 7 satellite): every [LOW-CONF] citation in
#: the package, verified against SURVEY.md §3. "consistent" = the survey
#: row itself marks the same symbol low-confidence, so the doc caveat is
#: faithful; "extrapolated" = the symbol does not appear in the survey's
#: row at all — the name is a plausible reconstruction beyond what the
#: survey attests. Either way, mount-absent status is blueprint-only;
#: re-verify (and upgrade to file:line) when the mount is populated.
_LOW_CONF_AUDIT = {
    ("crdt_tpu/traits.py", "ConflictingMarker"): (
        "consistent: SURVEY §3 row 8 itself marks the conflicting-marker "
        "error name [LOW-CONF on error name]"
    ),
    ("crdt_tpu/dot.py", "OrdDot"): (
        "consistent: SURVEY §3 row 3 itself marks OrdDot [LOW-CONF]"
    ),
    ("crdt_tpu/pure/lwwreg.py", "LWWOp"): (
        "consistent: SURVEY §3 row 8 pins update(val, marker) but not "
        "the CmRDT Op shape; §3.2 only requires the Op to exist"
    ),
    ("crdt_tpu/pure/identifier.py", "module"): (
        "consistent: SURVEY §3 row 12 itself marks the representation "
        "[LOW-CONF]; the LSEQ/Logoot-style design is the survey's"
    ),
    ("crdt_tpu/pure/identifier.py", "Identifier.value"): (
        "extrapolated: SURVEY §3 row 12 lists no `value` accessor — the "
        "name is inferred from GList's usage in row 14"
    ),
    ("crdt_tpu/pure/gcounter.py", "GCounter.inc_many"): (
        "extrapolated: SURVEY §3 row 5's symbol list (inc, apply, merge, "
        "read) has no inc_many — the name is inferred from the "
        "contiguous-dot semantics the row describes"
    ),
    ("crdt_tpu/vclock.py", "VClock.clone_without"): (
        "consistent: SURVEY §3 row 2 lists clone_without but marks the "
        "helper names [LOW-CONF]"
    ),
}

#: Maps a (file, line-content) match to its audit key — by the nearest
#: enclosing symbol named in the marker line's context.
_AUDIT_HINTS = (
    ("validate_merge", "ConflictingMarker"),
    ("OrdDot", "OrdDot"),
    ("CmRDT Op for LWWReg", "LWWOp"),
    ("Identifier::value", "Identifier.value"),
    ("between(lo, hi)", "module"),
    ("inc_many", "GCounter.inc_many"),
    ("clone_without", "VClock.clone_without"),
)


def low_conf_citations(root: str = ROOT) -> list:
    """Every ``[LOW-CONF …]`` marker under crdt_tpu/, each joined to its
    committed audit row (or flagged unaudited)."""
    out = []
    pkg = os.path.join(root, "crdt_tpu")
    for dirpath, dirnames, files in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
            for i, line in enumerate(lines, 1):
                m = _LOW_CONF_RE.search(line)
                if not m:
                    continue
                # Context = the marker line and its two predecessors
                # (citations wrap across docstring lines).
                ctx = "".join(lines[max(0, i - 3):i])
                symbol = next(
                    (sym for hint, sym in _AUDIT_HINTS if hint in ctx),
                    None,
                )
                audit = _LOW_CONF_AUDIT.get((rel, symbol))
                out.append({
                    "file": rel,
                    "line": i,
                    "marker": m.group(0),
                    "symbol": symbol,
                    "audit": audit or (
                        "UNAUDITED: add a row to "
                        "tools/check_reference.py _LOW_CONF_AUDIT"
                    ),
                })
    return out


def survey_cited_modules(survey_path: str) -> list:
    """Every `<name>.rs` SURVEY.md cites as a reference module file."""
    with open(survey_path, encoding="utf-8") as f:
        text = f.read()
    cited = set(re.findall(r"`(?:src/)?([a-z0-9_]+\.rs)`", text))
    return sorted(cited - _NON_MODULES)


def inventory(src_dir: str) -> dict:
    """``{file: line_count}`` for every .rs file under ``src_dir``."""
    out = {}
    for dirpath, _, files in os.walk(src_dir):
        for name in sorted(files):
            if not name.endswith(".rs"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, src_dir)
            with open(path, "rb") as f:
                out[rel] = f.read().count(b"\n")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--survey", default=os.path.join(ROOT, "SURVEY.md"))
    ap.add_argument(
        "--out", default=os.path.join(ROOT, "REFERENCE_CHECK.json")
    )
    args = ap.parse_args(argv)

    src = os.path.join(args.reference, "src")
    cited = survey_cited_modules(args.survey)
    low_conf = low_conf_citations()
    evidence = {
        "checked_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "reference": args.reference,
        "survey_cited_modules": cited,
        "low_conf_citations": low_conf,
    }
    unaudited = [c for c in low_conf if c["audit"].startswith("UNAUDITED")]

    inv = inventory(src) if os.path.isdir(src) else {}
    if not inv:
        evidence["mount"] = "absent-or-empty"
        evidence["low_conf_status"] = (
            "blueprint-only: the mount is absent, so every audited "
            "[LOW-CONF] citation stays a caveat against SURVEY.md §3 "
            "(which is itself low-confidence on these symbols) — "
            "nothing to upgrade against"
        )
        evidence["verdict"] = (
            "reference mount absent/empty; SURVEY.md remains the "
            "blueprint of record (SURVEY.md §0)"
        )
        rc = 0
        if unaudited:
            evidence["verdict"] = (
                f"{len(unaudited)} unaudited [LOW-CONF] citation(s) — "
                "audit them in tools/check_reference.py _LOW_CONF_AUDIT"
            )
            rc = 1
    else:
        missing = sorted(set(cited) - set(inv))
        uncited = sorted(set(inv) - set(cited))
        evidence.update(
            mount="populated",
            src_inventory=inv,
            cited_but_missing=missing,
            present_but_uncited=uncited,
        )
        evidence["low_conf_status"] = (
            f"mount populated: {len(low_conf)} [LOW-CONF] citation(s) "
            "are now upgrade work items — verify each against src/ and "
            "replace the marker with a file:line citation"
        )
        if missing or uncited or low_conf:
            evidence["verdict"] = (
                "inventory drift: re-verify SURVEY.md module table and "
                "upgrade citations to file:line (SURVEY.md §0)"
            )
            rc = 1
        else:
            evidence["verdict"] = "inventory matches SURVEY.md citations"
            rc = 0

    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(evidence, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"{evidence['verdict']} -> {args.out}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
