"""Reference-mount inventory check (SURVEY.md §0 provenance caveat).

SURVEY.md was reconstructed with the reference mount EMPTY, and its §0
mandates: "run `ls /root/reference/src`; if the mount is populated,
re-verify this inventory". This script is that step as a CI-runnable
tool:

    python tools/check_reference.py [--reference DIR] [--out FILE]

- Mount absent/empty: records that fact in the evidence artifact
  (REFERENCE_CHECK.json by default) and exits 0 — SURVEY.md stays the
  blueprint of record.
- Mount populated: inventories `src/*.rs`, diffs against the module
  files SURVEY.md cites, and writes both directions of the delta
  (cited-but-missing / present-but-uncited) plus per-file line counts
  so a reviewer can upgrade SURVEY.md citations to file:line. Exits 1
  on any delta so CI surfaces the drift.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Survey rows that are section/test globs, not src/ module files.
_NON_MODULES = {"build.rs"}


def survey_cited_modules(survey_path: str) -> list:
    """Every `<name>.rs` SURVEY.md cites as a reference module file."""
    with open(survey_path, encoding="utf-8") as f:
        text = f.read()
    cited = set(re.findall(r"`(?:src/)?([a-z0-9_]+\.rs)`", text))
    return sorted(cited - _NON_MODULES)


def inventory(src_dir: str) -> dict:
    """``{file: line_count}`` for every .rs file under ``src_dir``."""
    out = {}
    for dirpath, _, files in os.walk(src_dir):
        for name in sorted(files):
            if not name.endswith(".rs"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, src_dir)
            with open(path, "rb") as f:
                out[rel] = f.read().count(b"\n")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--survey", default=os.path.join(ROOT, "SURVEY.md"))
    ap.add_argument(
        "--out", default=os.path.join(ROOT, "REFERENCE_CHECK.json")
    )
    args = ap.parse_args(argv)

    src = os.path.join(args.reference, "src")
    cited = survey_cited_modules(args.survey)
    evidence = {
        "checked_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "reference": args.reference,
        "survey_cited_modules": cited,
    }

    inv = inventory(src) if os.path.isdir(src) else {}
    if not inv:
        evidence["mount"] = "absent-or-empty"
        evidence["verdict"] = (
            "reference mount absent/empty; SURVEY.md remains the "
            "blueprint of record (SURVEY.md §0)"
        )
        rc = 0
    else:
        missing = sorted(set(cited) - set(inv))
        uncited = sorted(set(inv) - set(cited))
        evidence.update(
            mount="populated",
            src_inventory=inv,
            cited_but_missing=missing,
            present_but_uncited=uncited,
        )
        if missing or uncited:
            evidence["verdict"] = (
                "inventory drift: re-verify SURVEY.md module table and "
                "upgrade citations to file:line (SURVEY.md §0)"
            )
            rc = 1
        else:
            evidence["verdict"] = "inventory matches SURVEY.md citations"
            rc = 0

    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(evidence, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"{evidence['verdict']} -> {args.out}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
