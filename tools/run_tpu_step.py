"""Run ONE named on-hardware check in this process and exit 0/1.

The monolithic ``tools/run_tpu_checks.py`` battery needs ~30 minutes of
continuous relay uptime, and rounds 3-5 all watched the relay tunnel die
mid-battery (a process's tunnel port is assigned at backend init; when
the tunnel process dies, every subsequent remote_compile in that process
is a connection-refused, so one relay hiccup erases the whole run).
This runner is the unit of the checkpointed capture strategy
(``tools/capture_tpu_evidence.py``): each step is small (one or two
Mosaic compiles), runs in a fresh process with a fresh tunnel, and
reports its own result — so a relay death costs one step, not the
battery.

    python tools/run_tpu_step.py <step>
    python tools/run_tpu_step.py --list

On success the LAST stdout line is a one-line human summary (sometimes
a JSON object) that the capture loop records as the step's detail.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _pallas_tests():
    spec = importlib.util.spec_from_file_location(
        "tpc", os.path.join(ROOT, "tests", "test_pallas_compiled.py")
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def _require_tpu():
    import jax

    backend = jax.default_backend()
    if backend not in ("tpu", "axon"):
        print(f"FAIL: backend is {backend}, not a TPU")
        sys.exit(1)
    # To stdout: the recorded PASS detail must carry the device proof.
    print(f"backend: {backend}, devices: {jax.devices()}")


def step_mosaic_fused():
    _require_tpu()
    _pallas_tests().test_fused_fold_compiles_and_matches_tree_on_tpu()
    print("compiled fused fold == tree fold (bit-identical on hardware)")


def step_mosaic_stream():
    _require_tpu()
    _pallas_tests().test_multi_pass_stream_compiles_on_tpu()
    print("multi-pass stream fold idempotent (compiled)")


def step_mosaic_map():
    _require_tpu()
    _pallas_tests().test_fused_map_fold_compiles_and_matches_tree_on_tpu()
    print("compiled Map<K, MVReg> fused fold == tree fold")


def step_mosaic_levels():
    _require_tpu()
    _pallas_tests().test_fused_level_folds_compile_and_match_tree_on_tpu()
    print("compiled map_orswot + map3 nested fused folds == tree folds")


def step_bench_fused():
    """The flagship: BASELINE config-3 full-scale streamed fused fold.
    Fails unless the fused Pallas path actually ran on the chip."""
    import bench

    _require_tpu()
    mps, path, gbps, nbytes, shape = bench.bench_tpu()
    if path != "fused":
        print(f"FAIL: path={path}, fused kernel did not run")
        sys.exit(1)
    print(json.dumps({
        "metric": "orswot_merges_per_sec", "value": round(mps, 1),
        "unit": "merges/s", "path": path, "gbps": round(gbps, 1),
        "bytes_moved": nbytes, "shape": shape,
    }))


def step_config4_map():
    os.environ.setdefault("BENCH_MAP_KEYS", "1000000")
    import bench

    _require_tpu()
    rec = bench.bench_map()
    if rec["path"] != "fused":
        print(f"FAIL: config4 path={rec['path']}")
        sys.exit(1)
    print(json.dumps(rec))


def step_config5_list():
    import bench

    _require_tpu()
    print(json.dumps(bench.bench_list()))


def step_sparse_1m():
    import bench

    _require_tpu()
    print(json.dumps(bench.bench_sparse()))


def step_sparse_map_100m():
    import bench

    _require_tpu()
    print(json.dumps(bench.bench_sparse_map()))


def step_npasses_ab():
    import run_tpu_checks

    _require_tpu()
    if not run_tpu_checks.npasses_streaming_ab():
        sys.exit(1)
    print("n_passes re-walk stream time-equivalent to distinct chunks, same bits")


def step_entry_compile():
    _require_tpu()
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    t0 = time.time()
    jax.jit(fn).lower(*args).compile()
    print(f"entry() compiles on hardware [{time.time()-t0:.0f}s]")


def step_crossover():
    _require_tpu()
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from sparse_crossover import run as crossover_run

    print(crossover_run())


STEPS = {
    "bench_fused": step_bench_fused,
    "mosaic_levels": step_mosaic_levels,
    "config4_map": step_config4_map,
    "config5_list": step_config5_list,
    "sparse_1m": step_sparse_1m,
    "sparse_map_100m": step_sparse_map_100m,
    "mosaic_fused": step_mosaic_fused,
    "mosaic_stream": step_mosaic_stream,
    "mosaic_map": step_mosaic_map,
    "npasses_ab": step_npasses_ab,
    "entry_compile": step_entry_compile,
    "crossover": step_crossover,
}


def main() -> int:
    if len(sys.argv) != 2 or sys.argv[1] in ("-h", "--help"):
        print(f"usage: {sys.argv[0]} <step>|--list", file=sys.stderr)
        return 2
    if sys.argv[1] == "--list":
        print("\n".join(STEPS))
        return 0
    name = sys.argv[1]
    if name not in STEPS:
        print(f"unknown step {name!r}; see --list", file=sys.stderr)
        return 2
    # tools/ on the path for run_tpu_checks import (npasses_ab).
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    STEPS[name]()
    return 0


if __name__ == "__main__":
    sys.exit(main())
