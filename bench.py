"""Benchmark of record: ORSWOT merges/sec, batched TPU fold vs the
sequential CPU oracle (BASELINE.md metric of record, config 3 shape
scaled to one chip).

Prints exactly ONE JSON line on stdout:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``
(all progress/diagnostics go to stderr).

Method: R replicas over an E-member universe with A actors, dense dot
matrices. TPU side times ``ops.fold`` (a log-tree of R-1 pairwise lattice
joins — the reference's ``Orswot::merge`` per SURVEY.md §4.2). CPU
baseline times the same serial merge fold through the pure oracle on a
smaller replica count (per-merge cost is replica-count independent:
every merge walks the same E-entry universe), reported as merges/sec.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# Scaled config-3 shape; override via env for full-size runs.
R = int(os.environ.get("BENCH_REPLICAS", 512))
E = int(os.environ.get("BENCH_ELEMS", 4096))
A = int(os.environ.get("BENCH_ACTORS", 8))
R_CPU = int(os.environ.get("BENCH_CPU_REPLICAS", 8))
ITERS = int(os.environ.get("BENCH_ITERS", 5))


def make_arrays(r):
    rng = np.random.default_rng(42)
    # ~70% of (element, actor) dots present — a well-mixed replica set.
    ctr = rng.integers(0, 100, (r, E, A)).astype(np.uint32)
    ctr[rng.random((r, E, A)) < 0.3] = 0
    top = np.maximum(ctr.max(axis=1), rng.integers(0, 100, (r, A)).astype(np.uint32))
    return top, ctr


def bench_tpu() -> float:
    import jax

    from crdt_tpu.ops import orswot as ops

    log(f"jax backend: {jax.default_backend()}, devices: {jax.devices()}")
    top, ctr = make_arrays(R)
    state = ops.empty(E, A, deferred_cap=4, batch=(R,))
    state = state._replace(
        top=jax.device_put(jax.numpy.asarray(top)),
        ctr=jax.device_put(jax.numpy.asarray(ctr)),
    )

    # Preferred path: the fused pallas fold (one HBM pass); fall back to
    # the jnp log-tree fold if the kernel cannot run here.
    fold = ops.fold
    if (
        jax.default_backend() in ("tpu", "axon")
        and os.environ.get("BENCH_FUSED", "1") != "0"
    ):
        try:
            from crdt_tpu.ops.pallas_kernels import fold_fused

            probe, _ = fold_fused(state)
            jax.block_until_ready(probe)
            fold = fold_fused
            log("using fused pallas fold")
        except Exception as exc:
            log(f"fused fold unavailable ({exc!r}); using tree fold")

    folded, _ = fold(state)  # compile + warm
    jax.block_until_ready(folded)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        folded, _ = fold(state)
        jax.block_until_ready(folded)
    dt = (time.perf_counter() - t0) / ITERS
    mps = (R - 1) / dt
    log(f"TPU fold: {R} replicas x {E} elems x {A} actors: {dt*1e3:.1f} ms/fold -> {mps:,.0f} merges/s")
    return mps


def bench_cpu() -> float:
    from crdt_tpu.pure.orswot import Orswot
    from crdt_tpu.vclock import VClock

    top, ctr = make_arrays(R_CPU)
    reps = []
    for i in range(R_CPU):
        o = Orswot()
        o.clock = VClock({a: int(c) for a, c in enumerate(top[i]) if c})
        for e in range(E):
            dots = {a: int(c) for a, c in enumerate(ctr[i, e]) if c}
            if dots:
                o.entries[e] = VClock(dots)
        reps.append(o)
    acc = Orswot()
    t0 = time.perf_counter()
    for r in reps:
        acc.merge(r)
    dt = time.perf_counter() - t0
    mps = R_CPU / dt
    log(f"CPU oracle fold: {R_CPU} merges over {E} elems: {dt*1e3:.1f} ms -> {mps:,.1f} merges/s")
    return mps


def make_edit_trace(n_ops: int, n_actors: int = 4, seed: int = 3):
    """An automerge-perf-shaped editing trace: mostly typing at a moving
    cursor, occasional jumps and deletes (BASELINE config 5)."""
    from crdt_tpu.native import DELETE, INSERT

    rng = np.random.default_rng(seed)
    kinds, idxs, vals, actors = [], [], [], []
    length, cursor = 0, 0
    for _ in range(n_ops):
        roll = rng.random()
        if length == 0 or roll < 0.72:       # type at cursor
            kinds.append(INSERT)
            idxs.append(cursor)
            cursor = min(cursor + 1, length + 1)
            length += 1
        elif roll < 0.87:                     # jump cursor
            cursor = int(rng.integers(0, length + 1))
            kinds.append(INSERT)
            idxs.append(cursor)
            cursor += 1
            length += 1
        else:                                 # backspace
            kinds.append(DELETE)
            victim = max(0, min(cursor - 1, length - 1))
            idxs.append(victim)
            cursor = victim
            length -= 1
        vals.append(int(rng.integers(0, 128)))
        actors.append(int(rng.integers(0, n_actors)))
    return kinds, idxs, vals, actors


def bench_list():
    """Config 5 (diagnostic, stderr): edit-trace ops/sec — pure-Python
    oracle vs native C++ engine vs device batched replicas."""
    from crdt_tpu.native import INSERT, ListEngine, native_available
    from crdt_tpu.pure.list import List

    n_ops = int(os.environ.get("BENCH_LIST_OPS", 20000))
    r = int(os.environ.get("BENCH_LIST_REPLICAS", 64))
    trace = make_edit_trace(n_ops)

    t0 = time.perf_counter()
    oracle = List()
    for k, ix, v, a in zip(*trace):
        op = (
            oracle.insert_index(ix, v, a)
            if k == INSERT
            else oracle.delete_index(ix, a)
        )
        oracle.apply(op)
    dt_py = time.perf_counter() - t0
    log(f"list config5: pure oracle {n_ops} ops: {dt_py*1e3:.0f} ms -> {n_ops/dt_py:,.0f} ops/s")

    t0 = time.perf_counter()
    engine = ListEngine()
    engine.apply_trace(*trace)
    dt_native = time.perf_counter() - t0
    log(
        f"list config5: native engine ({'C++' if engine.is_native else 'fallback'}) "
        f"{n_ops} ops: {dt_native*1e3:.0f} ms -> {n_ops/dt_native:,.0f} ops/s "
        f"({dt_py/dt_native:.1f}x oracle)"
    )

    import jax

    from crdt_tpu.models import BatchedList

    model = BatchedList.from_trace(*trace, n_replicas=r)
    t0 = time.perf_counter()
    model.apply_trace_to_all(chunk=2048)
    jax.block_until_ready(model.alive)
    dt_dev = time.perf_counter() - t0
    total = n_ops * r
    log(
        f"list config5: device batched {r} replicas x {n_ops} ops: "
        f"{dt_dev*1e3:.0f} ms -> {total/dt_dev:,.0f} replica-ops/s "
        f"({(total/dt_dev)/(n_ops/dt_py):.1f}x oracle rate)"
    )


def main():
    if os.environ.get("BENCH_LIST", "1") != "0":
        try:
            bench_list()
        except Exception as exc:  # diagnostic only — never kill the metric of record
            log(f"list bench failed: {exc!r}")
    cpu_mps = bench_cpu()
    tpu_mps = bench_tpu()
    print(
        json.dumps(
            {
                "metric": "orswot_merges_per_sec",
                "value": round(tpu_mps, 1),
                "unit": "merges/s",
                "vs_baseline": round(tpu_mps / cpu_mps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
