"""Benchmark of record: ORSWOT merges/sec, batched TPU fold vs the
sequential CPU oracle (BASELINE.md metric of record, config 3 shape
scaled to one chip).

Prints exactly ONE JSON line on stdout:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``
(all progress/diagnostics go to stderr).

Method: R replicas over an E-member universe with A actors, dense dot
matrices. TPU side times ``ops.fold`` (a log-tree of R-1 pairwise lattice
joins — the reference's ``Orswot::merge`` per SURVEY.md §4.2). CPU
baseline times the same serial merge fold through the pure oracle on a
smaller replica count (per-merge cost is replica-count independent:
every merge walks the same E-entry universe), reported as merges/sec.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# Scaled config-3 shape; override via env for full-size runs.
R = int(os.environ.get("BENCH_REPLICAS", 512))
E = int(os.environ.get("BENCH_ELEMS", 4096))
A = int(os.environ.get("BENCH_ACTORS", 8))
R_CPU = int(os.environ.get("BENCH_CPU_REPLICAS", 8))
ITERS = int(os.environ.get("BENCH_ITERS", 5))


def make_arrays(r):
    rng = np.random.default_rng(42)
    # ~70% of (element, actor) dots present — a well-mixed replica set.
    ctr = rng.integers(0, 100, (r, E, A)).astype(np.uint32)
    ctr[rng.random((r, E, A)) < 0.3] = 0
    top = np.maximum(ctr.max(axis=1), rng.integers(0, 100, (r, A)).astype(np.uint32))
    return top, ctr


def bench_tpu() -> float:
    import jax

    from crdt_tpu.ops import orswot as ops

    log(f"jax backend: {jax.default_backend()}, devices: {jax.devices()}")
    top, ctr = make_arrays(R)
    state = ops.empty(E, A, deferred_cap=4, batch=(R,))
    state = state._replace(
        top=jax.device_put(jax.numpy.asarray(top)),
        ctr=jax.device_put(jax.numpy.asarray(ctr)),
    )
    folded, _ = ops.fold(state)  # compile + warm
    jax.block_until_ready(folded)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        folded, _ = ops.fold(state)
        jax.block_until_ready(folded)
    dt = (time.perf_counter() - t0) / ITERS
    mps = (R - 1) / dt
    log(f"TPU fold: {R} replicas x {E} elems x {A} actors: {dt*1e3:.1f} ms/fold -> {mps:,.0f} merges/s")
    return mps


def bench_cpu() -> float:
    from crdt_tpu.pure.orswot import Orswot
    from crdt_tpu.vclock import VClock

    top, ctr = make_arrays(R_CPU)
    reps = []
    for i in range(R_CPU):
        o = Orswot()
        o.clock = VClock({a: int(c) for a, c in enumerate(top[i]) if c})
        for e in range(E):
            dots = {a: int(c) for a, c in enumerate(ctr[i, e]) if c}
            if dots:
                o.entries[e] = VClock(dots)
        reps.append(o)
    acc = Orswot()
    t0 = time.perf_counter()
    for r in reps:
        acc.merge(r)
    dt = time.perf_counter() - t0
    mps = R_CPU / dt
    log(f"CPU oracle fold: {R_CPU} merges over {E} elems: {dt*1e3:.1f} ms -> {mps:,.1f} merges/s")
    return mps


def main():
    cpu_mps = bench_cpu()
    tpu_mps = bench_tpu()
    print(
        json.dumps(
            {
                "metric": "orswot_merges_per_sec",
                "value": round(tpu_mps, 1),
                "unit": "merges/s",
                "vs_baseline": round(tpu_mps / cpu_mps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
